"""Batch scanner: the TPU-backed background-scan path.

This is the TPU-native replacement for the reference's per-resource scan
loop (reference: pkg/controllers/report/background/controller.go +
pkg/controllers/report/utils/scanner.go:60 ScanResource):

1. compile the policy set once (``compile_policies``)
2. project each resource onto the slot table (``encode_batch``)
3. run the jitted evaluator — a verdict sieve over [resources × rules]
4. synthesize responses for PASS / precondition-SKIP verdicts from
   compile-time templates; re-materialize FAIL / anchor-SKIP / HOST
   results with the host engine so messages and statuses are always
   bit-identical to a pure host run

Match/exclude is evaluated once per (kind, apiVersion, namespace) group
for rules whose match blocks only reference those fields — the common
case for background-scan policies — instead of once per (resource, rule)
pair (reference match semantics: pkg/engine/utils.go:185).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from ..engine.api import (EngineResponse, PolicyContext, RuleResponse,
                          RuleStatus, RuleType)
from ..engine.engine import Engine
from ..engine.match import matches_resource_description
from ..observability import coverage
from .. import faults
from . import admission as admission_lanes
from .compile import compile_policies
from .encode import encode_batch
from .shapes import canonical_capacity, canonical_caps
from .ir import (STATUS_FAIL, STATUS_HOST, STATUS_PASS, STATUS_SKIP,
                 STATUS_SKIP_PRECOND, STATUS_VAR_ERR, CompiledPolicySet,
                 RuleProgram)

_SIMPLE_MATCH_KEYS = {'kinds', 'namespaces', 'operations'}

#: the admission-shape warm resource: XLA compiles the evaluator once
#: per canonical batch capacity (compiler/shapes.py) and the element
#: axis clamps to a minimum of 4, so one ≤4-container warm pod covers
#: every ≤4-container admission request (the common case); larger pods
#: lazily compile their element width
WARM_POD = {
    'apiVersion': 'v1', 'kind': 'Pod',
    'metadata': {'name': 'warm', 'namespace': 'default'},
    'spec': {'containers': [
        {'name': f'c{i}', 'image': 'warm:1'} for i in range(2)]},
}

PRECONDITIONS_SKIP_MESSAGE = 'preconditions not met'

# sentinel: a device cell that must be re-run on the host engine
_HOST_MARKER = object()

#: process-unique monotonic scanner ids for batch coalescing keys —
#: ``id()`` can be reused after GC/eviction, which would let a fresh
#: scanner's tickets coalesce with a dead scanner's batch
_SCANNER_SERIALS = __import__('itertools').count(1)


def next_scanner_serial() -> int:
    """Next monotonic scanner serial (itertools.count: atomic in
    CPython).  Shared by BatchScanner and MutateScanner so the two
    program kinds can never collide on a serving key."""
    return next(_SCANNER_SERIALS)

# ---------------------------------------------------------------------------
# Encoder process pool: encode_batch is pure numpy/Python (no jax), so
# chunks encode in forked workers off the main interpreter's GIL — the
# assembly loop and the encoder no longer serialize against each other.

_ENCODER_CPS: Optional['CompiledPolicySet'] = None
_ENCODER_FORK_LOCK = __import__('threading').Lock()
#: per-worker-process arena: keeps the columnar value palettes warm
#: across the chunks a forked encoder serves (buffer pooling stays off
#: in workers — tensors are pickled back after return, so a recycled
#: buffer could be zeroed mid-serialization)
_ENCODER_PALETTES = None


def _encode_worker(args):
    global _ENCODER_PALETTES
    docs, contexts, padded_n = args
    if _ENCODER_PALETTES is None:
        from .encode import LaneArena
        _ENCODER_PALETTES = LaneArena(max_pool=0)
    # the fork inherits the parent's telemetry globals, but its metric
    # increments and contextvars die with the process — the pipeline
    # threads re-install the scan's ScanCapture, and this is the
    # process-side analogue: measure into a fresh local capture and
    # ship the stage seconds (plus the wall interval, for the
    # timeline) home with the tensors; the resolving pipeline thread
    # re-attributes them via devtel.merge_worker_stages.
    from ..observability import device as devtel
    cap = devtel.ScanCapture()
    t0 = time.monotonic()
    with devtel.install_capture(cap):
        batch = encode_batch(docs, _ENCODER_CPS, padded_n=padded_n,
                             contexts=contexts, arena=_ENCODER_PALETTES)
    t1 = time.monotonic()
    cap.add('encode', t1 - t0)
    return batch.tensors(), dict(cap.stages), \
        (t0, t1, __import__('os').getpid())


class _EncoderPool:
    """Lazy forked pool; falls back to in-process encoding on failure."""

    def __init__(self, cps, procs: int):
        self.cps = cps
        self.procs = procs
        self._pool = None
        self._broken = False

    def start(self) -> bool:
        if self._broken or self.procs <= 0:
            return False
        if self._pool is None:
            global _ENCODER_CPS
            try:
                import multiprocessing as mp
                import weakref
                with _ENCODER_FORK_LOCK:
                    # the global must stay pinned to this cps until the
                    # fork snapshots it — concurrent pool starts from
                    # other scanners would capture the wrong policy set
                    _ENCODER_CPS = self.cps
                    pool = mp.get_context('fork').Pool(self.procs)
                self._pool = pool
                # weakref.finalize runs at collection OR interpreter exit
                # (atexit=True default), so workers are reaped when the
                # scanner is dropped and mp.Pool.__del__ never races the
                # shutdown pickler
                self._finalizer = weakref.finalize(self, pool.terminate)
            except Exception:  # noqa: BLE001 - pool is an optimization
                self._broken = True
                return False
        return True

    def submit(self, docs, contexts, padded_n):
        return self._pool.apply_async(_encode_worker,
                                      ((docs, contexts, padded_n),))

    def close(self) -> None:
        if self._pool is not None:
            fin = getattr(self, '_finalizer', None)
            if fin is not None:
                fin()  # idempotent: terminates the pool once
            else:
                self._pool.terminate()
            self._pool = None


_LABEL_MATCH_KEYS = _SIMPLE_MATCH_KEYS | {'selector'}


def _rule_match_is_simple(rule: dict, keys=_SIMPLE_MATCH_KEYS) -> bool:
    """True when match/exclude depend only on kind/apiVersion/namespace."""
    def block_simple(block: dict) -> bool:
        for f in [block] + (block.get('any') or []) + (block.get('all') or []):
            res = f.get('resources') or {}
            if any(k not in keys for k in res):
                return False
            if f.get('roles') or f.get('clusterRoles') or f.get('subjects'):
                return False
        return True
    return block_simple(rule.get('match') or {}) and \
        block_simple(rule.get('exclude') or {})


def _rule_match_is_label_simple(rule: dict) -> bool:
    """True when match/exclude additionally reference only the resource's
    label selector — the decision is a function of (group key, labels),
    so selector-heavy policies cache per distinct label set instead of
    per resource (the adversarial regime for the group cache)."""
    return _rule_match_is_simple(rule, _LABEL_MATCH_KEYS)


def policy_namespace_gate(policy: Policy, res: Resource) -> bool:
    """Namespaced policies only apply inside their own namespace
    (engine.py:230-236, reference: pkg/engine/validation.go:117).
    Shared by the scan and bulk-apply match sieves."""
    if not policy.is_namespaced:
        return True
    return bool(res.namespace) and res.namespace == policy.namespace


def _group_key(doc: dict) -> Tuple[str, str, str]:
    meta = doc.get('metadata') or {}
    return (str(doc.get('kind', '')), str(doc.get('apiVersion', '')),
            str(meta.get('namespace', '') or ''))


class BatchScanner:
    """Compiles a policy set once and evaluates resource batches on device.

    ``scan`` returns the full per-resource engine responses (bit-identical
    to the host engine); ``scan_statuses`` returns just the raw device
    verdict matrices for throughput-critical callers.
    """

    def __init__(self, policies: List[Policy], engine: Optional[Engine] = None,
                 mesh=None):
        self.policies = policies
        self.engine = engine or Engine()
        self.cps: CompiledPolicySet = compile_policies(policies)
        self.mesh = mesh
        # policies needing the host engine for at least one rule, plus
        # applyRules=One policies (early-exit coupling between rules)
        self._host_policy_idx = sorted(
            {i for i, _, _ in self.cps.host_rules} |
            {i for i, p in enumerate(policies)
             if (p.apply_rules or 'All') == 'One'})
        host_set = set(self._host_policy_idx)
        # device-synthesizable programs (their whole policy compiled)
        self.device_programs: List[Tuple[int, RuleProgram]] = [
            (j, prog) for j, prog in enumerate(self.cps.programs)
            if prog.policy_index not in host_set]
        self._dev_mask = np.zeros(len(self.cps.programs), bool)
        for _j, _ in self.device_programs:
            self._dev_mask[_j] = True
        # final per-rule placement (compile placements + the policy-
        # coupling override above); feeds the coverage ledger and the
        # host-run fallback attribution below
        self._placements = coverage.compile_placements(policies, self.cps)
        self._host_rule_reason = {
            (pl.policy, pl.rule): (pl.reason or
                                   coverage.REASON_POLICY_COUPLING,
                                   pl.path)
            for pl in self._placements
            if pl.placement == coverage.PLACEMENT_HOST}
        if coverage.enabled():
            coverage.record_placements(self._placements)
        # the AOT-cache fingerprint of this scanner's policy set —
        # decision-provenance records carry it so a flight-recorder
        # line names exactly which compiled set served the decision
        from ..aotcache.keys import policy_set_fingerprint
        self.fingerprint = policy_set_fingerprint(policies)
        from ..ops.eval import build_evaluator
        self._evaluator = build_evaluator(self.cps)
        # per-row admission lanes (compiler/admission.py): the serving
        # batch key is the scanner alone, so mixed-user/mixed-verb
        # bursts share one dispatch; the evaluator owns the compiled
        # table (single source — the lane signature and the in-graph
        # decision can never disagree)
        self.serial = next_scanner_serial()
        self.supports_row_admissions = True
        self._adm = getattr(self._evaluator, 'adm_table', None)
        self._adm_cols = self._evaluator.adm_cols \
            if self._adm is not None else None
        # partitioned compile (KTPU_PARTITIONS > 0, non-mesh): one
        # evaluator per policy-group partition, AOT-keyed by the
        # partition fingerprint (kyverno_tpu/partition/), per-partition
        # outputs merged back into the whole-set verdict contract by the
        # composer.  Any structural mismatch falls back to the
        # monolithic evaluator above — never a wrong verdict.  The
        # whole-set evaluator stays as assembly metadata (any_meta,
        # n_cols, dev masks); jax.jit is lazy, so it never compiles
        # unless the fallback actually dispatches it.
        self._pset = None
        self._composer = None
        from ..partition.plan import PartitionError, env_partitions
        _n_parts = env_partitions()
        if _n_parts > 0 and mesh is None and self.cps.programs:
            try:
                from ..partition import census as _census
                from ..partition.compose import Composer
                from ..partition.runtime import build_runtime
                _pset = build_runtime(policies, self.cps, _n_parts,
                                      set_fingerprint=self.fingerprint)
                self._composer = Composer(self._evaluator,
                                          _pset.runtimes)
                self._pset = _pset
            except PartitionError:
                from ..observability.metrics import global_registry
                from ..partition.runtime import PARTITION_FALLBACKS
                _reg = global_registry()
                if _reg is not None:
                    _reg.inc(PARTITION_FALLBACKS)
            else:
                # partitioned dispatches ship no whole-set in-graph
                # admission lanes: with self._adm None no
                # AdmissionRowPlan is ever built and the host matcher
                # decides admission rows exactly — plan=None semantics,
                # bit-identical to the monolithic oracle
                self._adm = None
                self._adm_cols = None
                _census.record_plan(self.fingerprint, _pset.plan,
                                    serial=self.serial)
        from collections import OrderedDict
        self._simple_match = [
            _rule_match_is_simple(p.rule_raw or {}) for p in self.cps.programs]
        self._label_match = [
            not s and _rule_match_is_label_simple(p.rule_raw or {})
            for s, p in zip(self._simple_match, self.cps.programs)]
        # LRU-bounded: one row per (kind, apiVersion, namespace, operation)
        # group — long-lived admission scanners in many-namespace clusters
        # must not grow without bound.  Locked: webhook threads share one
        # scanner and race get/evict/move_to_end otherwise.
        self._match_cache: 'OrderedDict[Tuple, np.ndarray]' = OrderedDict()
        self._match_cache_max = 4096
        self._match_cache_lock = __import__('threading').Lock()
        self._rules = [Rule(p.rule_raw or {}) for p in self.cps.programs]
        self._fail_msg_cache: Dict[Tuple, Optional[str]] = {}
        # forked encode workers only pay off with spare cores: on a
        # single-CPU host the ~150MB/chunk lane tensors pickled back
        # through the pipe cost more CPU than the encode they offload
        _os = __import__('os')
        _default_procs = '2' if (_os.cpu_count() or 1) > 2 else '0'
        self._encoder_pool = _EncoderPool(
            self.cps,
            int(_os.environ.get('KTPU_ENCODE_PROCS', _default_procs)))
        # static per-policy response header fields (avoids re-deriving
        # them from the raw policy dict per (resource, policy) pair)
        self._policy_header = [
            (p, p.name, p.namespace, p.validation_failure_action,
             p.validation_failure_action_overrides) for p in policies]
        # reusable encode buffers + cross-chunk value palettes for the
        # streaming pipeline (compiler/encode.py LaneArena): chunk lane
        # tensors recycle instead of reallocating ~100MB per chunk
        from .encode import LaneArena
        self._arena = LaneArena()

    def warmup(self, resources: Optional[List[dict]] = None) -> float:
        """Bring the admission-shape executable to serving readiness.

        Runs one scan over ``resources`` (default: the shared
        ``WARM_POD``), which walks the whole pipeline — encode, pack,
        h2d, executable lookup, device eval, d2h, assembly.  The
        executable lookup consults the persistent AOT store first
        (``aot_load`` instead of ``miss`` when a prior process already
        compiled this policy set), so a warm cache makes this seconds
        instead of a fresh multi-second XLA compile.  Returns the
        elapsed wall-clock seconds."""
        import copy
        t0 = time.monotonic()
        self.scan([copy.deepcopy(r) for r in (resources or [WARM_POD])])
        return time.monotonic() - t0

    def warmup_shapes(self, caps: Optional[List[int]] = None
                      ) -> Dict[int, float]:
        """Bring EVERY canonical batch capacity to serving readiness.

        One warm dispatch per capacity in the canonical shape table
        (``compiler/shapes.py``), run on a small thread pool: each
        dispatch drives the evaluator with exactly the tensor signature
        a real scan at that capacity produces (lanes + ``__rowvalid__``
        + the unique-space ``__match__`` plane), so the executable
        lookup — persistent AOT store first, fresh compile otherwise —
        is the one live traffic will hit.  Deserializes don't hold the
        evaluator's compile lock, so a warm disk cache loads the whole
        table in ~max(entry) instead of sum(entries).  Returns
        {capacity: seconds}."""
        import copy
        from concurrent.futures import ThreadPoolExecutor
        from ..ops.eval import shard_batch
        if not self.cps.programs:
            return {}
        table = sorted(set(caps if caps is not None else canonical_caps(
            chunk=self.CHUNK, small=self.SMALL_BATCH)))

        def warm_partitions(cap: int) -> float:
            # partitioned mode warms each partition's evaluator with
            # the exact tensor signature the partitioned scan path
            # produces (per-partition lanes + __rowvalid__ + the
            # partition-local unique-space __match__ plane + the
            # partition's admission lanes when it has any)
            t0 = time.monotonic()
            device = self._small_device() \
                if self.mesh is None and cap <= self.SMALL_BATCH else None
            for rt in self._pset.runtimes:
                batch = encode_batch([copy.deepcopy(WARM_POD)],
                                     rt.sub_cps, padded_n=cap)
                tensors = batch.tensors()
                tensors['__match__'] = np.zeros(
                    (cap, rt.evaluator.n_uniq), np.uint8)
                if rt.adm is not None:
                    tensors.update(admission_lanes.zero_lanes(
                        rt.adm, cap))
                t, layout = shard_batch(tensors, None, device=device)
                out = rt.evaluator(t, layout)
                for arr in out:
                    np.asarray(arr)
                self._free_inputs(t, out)
            return time.monotonic() - t0

        def warm_one(cap: int) -> float:
            if self._composer is not None:
                return warm_partitions(cap)
            t0 = time.monotonic()
            batch = encode_batch([copy.deepcopy(WARM_POD)], self.cps,
                                 padded_n=cap)
            tensors = batch.tensors()
            if self.mesh is None:
                # mirror dispatch_work: non-mesh dispatches always ship
                # the unique-space match plane (values are irrelevant
                # for warming; the SIGNATURE selects the executable)
                tensors['__match__'] = np.zeros(
                    (cap, self._evaluator.n_uniq), np.uint8)
                if self._adm is not None:
                    # admission lanes are part of the signature too
                    tensors.update(admission_lanes.zero_lanes(
                        self._adm, cap))
            device = self._small_device() \
                if self.mesh is None and cap <= self.SMALL_BATCH else None
            t, layout = shard_batch(tensors, self.mesh, device=device)
            out = self._evaluator(t, layout)
            for arr in out:
                np.asarray(arr)  # materialize before freeing inputs
            self._free_inputs(t, out)
            return time.monotonic() - t0

        if len(table) <= 1:
            return {cap: warm_one(cap) for cap in table}
        with ThreadPoolExecutor(
                max_workers=min(4, len(table)),
                thread_name_prefix='ktpu-shape-warm') as pool:
            futs = [(cap, pool.submit(warm_one, cap)) for cap in table]
            return {cap: f.result() for cap, f in futs}

    # -- match --------------------------------------------------------------

    def _policy_gate(self, policy: Policy, res: Resource) -> bool:
        return policy_namespace_gate(policy, res)

    def _match_one(self, j: int, res: Resource,
                   admission: Optional[tuple] = None) -> bool:
        prog = self.cps.programs[j]
        policy = self.policies[prog.policy_index]
        if not self._policy_gate(policy, res):
            return False
        info, roles, ns_labels = admission or (None, [], {})
        return matches_resource_description(
            res, self._rules[j], info, roles, ns_labels, '') is None

    def _mcache_get(self, key):
        with self._match_cache_lock:
            hit = self._match_cache.get(key)
            if hit is not None:
                self._match_cache.move_to_end(key)
            return hit

    def _mcache_put(self, key, value):
        with self._match_cache_lock:
            while len(self._match_cache) >= self._match_cache_max:
                self._match_cache.popitem(last=False)
            self._match_cache[key] = value

    def _adm_res_atoms(self, resources: List[dict],
                       wrapped: List[Resource]) -> np.ndarray:
        """[R, F] uint8 resource-shape atoms for the admission-eligible
        filters (compiler/admission.py), group-cached: eligible filters
        only reference kinds/namespaces/operations plus the policy
        namespace gate, all functions of the resource group."""
        table = self._adm
        n = len(resources)
        out = np.zeros((n, len(table.atoms)), np.uint8)
        groups: Dict[Tuple, List[int]] = {}
        for i, doc in enumerate(resources):
            groups.setdefault(_group_key(doc), []).append(i)
        for key, idxs in groups.items():
            ck = ('admres',) + key
            cached = self._mcache_get(ck)
            if cached is None:
                rep = wrapped[idxs[0]]
                cached = np.array([
                    1 if admission_lanes.atom_ok(
                        a, self.policies[a.policy_index], rep) else 0
                    for a in table.atoms], np.uint8)
                self._mcache_put(ck, cached)
            out[idxs, :] = cached
        return out

    def match_matrix(self, resources: List[dict], wrapped: List[Resource],
                     admission: Optional[tuple] = None,
                     adm_rows: Optional[List[Optional[tuple]]] = None,
                     plan: Optional[Any] = None) -> np.ndarray:
        """[R, P] bool match mask, group-cached for simple-match rules.
        ``admission`` carries one scan-wide (admission_info,
        exclude_group_roles, namespace_labels, operation) tuple;
        ``adm_rows`` carries one PER ROW (heterogeneous webhook
        batches).  Simple-match rules only reference
        kinds/namespaces/operations, so the group cache stays valid
        across mixed users with each row's operation folded into its
        own key.  ``plan`` (AdmissionRowPlan) marks rows whose
        admission-eligible columns the jitted evaluator will decide
        in-graph: those cells hold the conservative upper bound here
        and are replaced with the exact device decision before
        assembly; non-valid rows (unencodable admission values, UPDATE
        rows) fall back to the host matcher per row."""
        n = len(resources)
        p = len(self.cps.programs)
        match = np.zeros((n, p), bool)
        if p == 0:
            return match
        simple = np.asarray(self._simple_match)
        if adm_rows is None and admission is not None:
            adm_rows = [admission] * n
        if adm_rows is not None:
            ops = [a[3] if isinstance(a, tuple) and len(a) > 3 else ''
                   for a in adm_rows]
            adm3s = [tuple(a[:3]) if isinstance(a, tuple) else None
                     for a in adm_rows]
        else:
            ops = [''] * n
            adm3s: List[Optional[tuple]] = [None] * n
        # group resources by (kind, apiVersion, namespace, operation) —
        # per-row operations, so mixed-verb batches group correctly
        groups: Dict[Tuple, List[int]] = {}
        for i, doc in enumerate(resources):
            groups.setdefault(_group_key(doc) + (ops[i],), []).append(i)
        for key, idxs in groups.items():
            cached = self._mcache_get(key)
            if cached is None:
                rep = wrapped[idxs[0]]
                rep_adm = adm3s[idxs[0]]
                cached = np.array([
                    self._match_one(j, rep, rep_adm) if simple[j] else False
                    for j in range(p)])
                self._mcache_put(key, cached)
            match[idxs, :] = cached
        # label-selector rules: the decision depends only on (group,
        # labels) — cache per distinct label set (cardinality of label
        # combinations, not of resources)
        label_js = np.nonzero(np.asarray(self._label_match))[0]
        if label_js.size:
            for i, doc in enumerate(resources):
                labels = (doc.get('metadata') or {}).get('labels') or {}
                lkey = (_group_key(doc), ops[i],
                        tuple(sorted(labels.items())))
                cached = self._mcache_get(lkey)
                if cached is None:
                    cached = np.array([
                        self._match_one(int(j), wrapped[i], adm3s[i])
                        for j in label_js])
                    self._mcache_put(lkey, cached)
                match[i, label_js] = cached
        # remaining non-simple rules (names, annotations, wildcard
        # namespaces, roles): evaluate per resource with that row's own
        # admission tuple — except admission-eligible columns of device-
        # valid rows, which the evaluator decides in-graph
        rest = ~simple & ~np.asarray(self._label_match)
        dev_cols: Dict[int, int] = {}
        if plan is not None and self._adm_cols is not None:
            dev_cols = {int(j): c for c, j in enumerate(self._adm_cols)}
        for j in np.nonzero(rest)[0]:
            j = int(j)
            c = dev_cols.get(j)
            if c is not None:
                up = plan.upper[:, c]
                for i in range(n):
                    match[i, j] = up[i] if plan.valid[i] else \
                        self._match_one(j, wrapped[i], adm3s[i])
            else:
                for i in range(n):
                    match[i, j] = self._match_one(j, wrapped[i], adm3s[i])
        return match

    def _fold_old_matches(self, match: np.ndarray,
                          wrapped: List[Resource],
                          adm_rows: Optional[List[Optional[tuple]]],
                          old_resources) -> np.ndarray:
        """UPDATE-verb match semantics folded into the sieve: the engine
        retries a failed new-object match against the old object
        (engine.py:303 ``_matches``), and a namespaced policy applies
        only when BOTH objects sit in its namespace (engine.py:239).
        The old objects run through ``match_matrix`` themselves, so the
        group cache amortizes the retry across a batch exactly like the
        new-object sieve (the per-(row, program) host walk this
        replaced dominated mixed-verb batches at 1k policies)."""
        rows = [i for i, old in enumerate(old_resources) if old]
        if not rows:
            return match
        old_docs = [old_resources[i] for i in rows]
        old_wrapped = [Resource(d) for d in old_docs]
        sub_adm = [adm_rows[i] for i in rows] if adm_rows is not None \
            else None
        om = self.match_matrix(old_docs, old_wrapped, adm_rows=sub_adm)
        match = match.copy()
        ridx = np.asarray(rows)
        match[ridx] |= om
        progs = self.cps.programs
        for j in range(len(progs)):
            policy = self.policies[progs[j].policy_index]
            if not policy.is_namespaced:
                continue  # both-object gate is vacuous
            for k, i in enumerate(rows):
                if match[i, j] and not (
                        self._policy_gate(policy, wrapped[i]) and
                        self._policy_gate(policy, old_wrapped[k])):
                    match[i, j] = False
        return match

    # -- device evaluation --------------------------------------------------

    #: fixed device-chunk size: XLA compiles the evaluator once per
    #: distinct batch shape, so large scans stream fixed-size chunks.
    #: 16k beats 8k by ~30% on the remote-TPU tunnel — per-chunk d2h
    #: round-trip latency amortizes over more rows
    CHUNK = int(__import__('os').environ.get('KTPU_SCAN_CHUNK', '16384'))
    #: batches at or below this size run on the host-local CPU backend:
    #: a single admission request must not pay a remote-accelerator
    #: round trip (latency floor), while bulk scans amortize it
    SMALL_BATCH = int(__import__('os').environ.get(
        'KTPU_SMALL_BATCH', '64'))
    #: upper bound on one forked-encoder chunk (normal: ~2s); beyond this
    #: the worker is presumed dead and the chunk re-encodes in-process
    ENCODE_TIMEOUT_S = float(__import__('os').environ.get(
        'KTPU_ENCODE_TIMEOUT', '120'))

    @staticmethod
    def _free_inputs(t, out) -> None:
        """Free each chunk's device input (and consumed output) buffers
        eagerly: the remote-TPU tunnel client defers buffer release long
        enough that a 1M-pod stream retained ~one chunk of host staging
        memory per chunk processed (~20GB peak RSS) — outputs are
        already materialized as numpy copies by the callers."""
        try:
            for arr in t.values():
                if hasattr(arr, 'delete'):
                    arr.delete()
            for arr in out:
                if hasattr(arr, 'delete'):
                    arr.delete()
        except Exception:  # noqa: BLE001 - freeing is best-effort
            pass

    def _small_device(self):
        import jax
        try:
            if jax.default_backend() != 'cpu':
                return jax.local_devices(backend='cpu')[0]
        except Exception:  # noqa: BLE001 - no cpu backend registered
            return None
        return None

    def _device_status_chunks(self, resources: List[dict],
                              contexts: Optional[List[dict]] = None,
                              match: Optional[np.ndarray] = None,
                              adm_plan: Optional[Any] = None,
                              match_fn=None, timeline=None):
        """Yield ``(start, status, detail, fdet, adm, chunk_match)`` per
        fixed-size chunk; ``adm`` is the device's per-row
        admission-match decision for the eligible program columns (None
        off the compact path or when the policy set has none).

        The chunks stream through a bounded overlapped pipeline
        (``compiler/pipeline.py``): encode → h2d → device_eval → d2h
        each run on their own worker thread with at most
        ``KTPU_PIPELINE_DEPTH`` chunks in flight, so end-to-end rate ≈
        max(stage) instead of sum(stage) and a slow leg backpressures
        intake instead of buffering.  Encode lane tensors are recycled
        through the scanner's :class:`LaneArena` — a chunk's buffers
        return to the pool when its d2h lands, so RSS stays flat in
        ``n_resources``.

        ``match`` (the host-side [R, P] match mask) rides to the device
        with each chunk so fail details compact to the (matched, FAIL)
        cells — d2h bytes drop ~3× over a remote-TPU tunnel.
        ``match_fn(start, part)`` computes the mask per chunk inside
        the encode stage instead (streaming callers avoid holding the
        full [R, P] matrix)."""
        n = len(resources)
        if not self.cps.programs or not resources:
            z = np.zeros((n, len(self.cps.programs)), np.int8)
            zm = match[:n] if match is not None \
                else np.zeros((n, len(self.cps.programs)), bool)
            yield 0, z, z, z.astype(np.int32), None, zm
            return
        if self._composer is not None:
            yield from self._partitioned_status_chunks(
                resources, contexts, match, match_fn, timeline)
            return
        from ..observability import device as devtel
        from ..observability import timeline as tlmod
        from ..observability import tracing
        from ..ops.eval import expand_compact, shard_batch
        from .pipeline import ChunkPipeline
        chunk = self.CHUNK
        small = self.mesh is None and n <= self.SMALL_BATCH
        device = self._small_device() if small else None
        # pipeline stages run on worker threads where the contextvar
        # span is absent — capture the request/scan span here so every
        # stage span joins the caller's trace (and the provenance
        # capture, so multi-chunk scans attribute worker-thread stage
        # time to the right scan)
        tel_parent = tracing.current_span()
        tel_capture = devtel.current_capture()
        arena = self._arena if self.mesh is None else None

        # multi-chunk scans encode in forked worker processes (off-GIL);
        # small scans stay in-process
        use_procs = n > chunk and self._encoder_pool.start()

        def inline_encode(part, part_ctx, bucket):
            with devtel.stage('encode', {'rows': len(part)}):
                batch = encode_batch(part, self.cps, padded_n=bucket,
                                     contexts=part_ctx, arena=arena)
                return batch.tensors(), batch

        def release_chunk(p):
            """Return a chunk's encode buffers to the arena exactly
            once — after d2h frees its device inputs on the success
            path, or via the pipeline's cleanup hook when the chunk
            dies mid-flight (stage crash, aborted stream).  Device
            references are dropped first so a zero-copy h2d path never
            sees its backing buffer recycled while still reachable."""
            if not isinstance(p, dict):
                return
            p['t'] = p['out'] = p['enc'] = None
            batch = p.get('batch')
            p['batch'] = None
            if arena is not None and batch is not None:
                arena.release(batch)

        def stage_encode(start):
            faults.check(faults.SITE_ENCODE)
            part = resources[start:start + chunk]
            part_ctx = contexts[start:start + chunk] \
                if contexts is not None else None
            cm = match[start:start + len(part)] if match is not None \
                else (match_fn(start, part) if match_fn is not None
                      else None)
            # canonical capacity padding (compiler/shapes.py): every
            # part pads to one of the few canonical row shapes and the
            # evaluator masks the tail rows via the __rowvalid__ lane,
            # so XLA never sees a new shape whatever the occupancy.
            # Multi-chunk scans pin every part (tail included) to the
            # chunk capacity: their dispatches skip the small-batch CPU
            # placement, so a canonically-small tail would otherwise
            # compile one extra shape on the accelerator backend.
            bucket = chunk if n > chunk else canonical_capacity(
                len(part), chunk=chunk, small=self.SMALL_BATCH)
            enc = batch = None
            if use_procs:
                try:
                    enc = self._encoder_pool.submit(part, part_ctx,
                                                    bucket)
                except Exception:  # noqa: BLE001 - fall back in-process
                    enc = None
            if enc is None:
                enc, batch = inline_encode(part, part_ctx, bucket)
            return {'start': start, 'ln': len(part), 'part': part,
                    'part_ctx': part_ctx, 'bucket': bucket, 'enc': enc,
                    'batch': batch, 'cm': cm}

        def stage_h2d(p):
            faults.check(faults.SITE_H2D)
            start, ln = p['start'], p['ln']
            tensors = p['enc']
            devtel.set_batch_size(ln)
            if not isinstance(tensors, dict):
                # AsyncResult from the fork pool: a dead/OOM-killed worker
                # never resolves its task, so bound the wait and redo the
                # chunk in-process rather than wedging the whole scan
                if self._encoder_pool._broken:
                    # pool already declared dead: don't wait another
                    # timeout per in-flight chunk
                    tensors, p['batch'] = inline_encode(
                        p['part'], p['part_ctx'], p['bucket'])
                else:
                    try:
                        tensors, wstages, wspan = tensors.get(
                            timeout=self.ENCODE_TIMEOUT_S)
                    except Exception:  # noqa: BLE001 - worker death
                        self._encoder_pool.close()
                        self._encoder_pool._broken = True
                        tensors, p['batch'] = inline_encode(
                            p['part'], p['part_ctx'], p['bucket'])
                    else:
                        # stage seconds measured inside the forked
                        # worker: fold into the parent's histogram and
                        # the ambient ScanCapture (installed on this
                        # pipeline thread), and pin the worker's wall
                        # interval on the timeline with its process
                        # identity — fork workers share the parent's
                        # monotonic clock on Linux
                        devtel.merge_worker_stages(wstages)
                        if timeline is not None and wspan is not None:
                            timeline.record(
                                'encode', start // chunk, wspan[0],
                                wspan[1],
                                thread='ktpu-encproc-%d' % wspan[2])
            cm = p['cm']
            if cm is not None and self.mesh is None and tensors:
                from ..ops.eval import fold_match_unique
                padded = next(iter(tensors.values())).shape[0]
                # host-policy program columns are never read from fdet
                # (_assemble_chunk ANDs with _dev_mask) — keep their
                # FAIL cells out of the per-row compaction budget; the
                # mask rides in UNIQUE-program space (duplicate columns
                # OR-folded) so the device graph and d2h stay O(unique)
                mm_p = (cm & self._dev_mask).astype(np.uint8)
                mm_u = fold_match_unique(mm_p, self._evaluator)
                mm = np.zeros((padded, mm_u.shape[1]), np.uint8)
                mm[:ln] = mm_u
                tensors = dict(tensors)
                tensors['__match__'] = mm
            if self._adm is not None and self.mesh is None and tensors:
                # admission lanes ride EVERY non-mesh dispatch of this
                # policy set (zero-filled when the scan carries no
                # admission data) so the executable signature — and the
                # fresh-process census — never depends on traffic mix
                padded = next(iter(tensors.values())).shape[0]
                tensors = dict(tensors)
                if adm_plan is not None:
                    tensors.update(admission_lanes.slice_lanes(
                        adm_plan.lanes, start, ln, padded))
                else:
                    tensors.update(admission_lanes.zero_lanes(
                        self._adm, padded))
            t, layout = shard_batch(tensors, self.mesh, device=device)
            p['enc'] = p['part'] = p['part_ctx'] = None
            p['t'], p['layout'] = t, layout
            return p

        def stage_eval(p):
            faults.check(faults.SITE_DEVICE_EVAL)
            p['out'] = self._evaluator(p['t'], p['layout'])
            return p

        def stage_d2h(p):
            faults.check(faults.SITE_D2H)
            start, ln, t, out = p['start'], p['ln'], p['t'], p['out']
            if len(out) == 2:
                # np.array COPIES: np.asarray of a host-backend jax
                # array is zero-copy, and _free_inputs is about to
                # release the backing buffers
                with devtel.d2h_guard({'chunk_start': start,
                                       'rows': ln}) as g:
                    o8 = np.array(out[0])
                    o32 = np.array(out[1])
                    g.add_d2h_bytes(o8.nbytes + o32.nbytes)
                s, d, fd, adm = expand_compact(o8, o32,
                                               self._evaluator)
                self._free_inputs(t, out)
                cm = p['cm']
                release_chunk(p)
                return (start, s[:ln], d[:ln], fd[:ln],
                        adm[:ln] if adm is not None else None, cm)
            s, d, fd = out
            if self.mesh is not None:
                import jax
                from ..observability import fleet
                shard_walls = None
                t_coll = 0.0
                padded_rows = int(s.shape[0])
                if fleet.enabled():
                    # mesh-path telemetry (fleet observatory): time
                    # each shard's readback wait, then the collective
                    # leg — pure timing, the values are untouched
                    from ..parallel.mesh import shard_wait_splits
                    shard_walls = shard_wait_splits(s)
                    t_coll = time.perf_counter()
                if jax.process_count() > 1:
                    # multi-host mesh: each process only holds its
                    # local shards of the batch axis — gather the
                    # full matrices so every host assembles
                    # identical reports (the reference replicates
                    # this work per replica)
                    from jax.experimental import multihost_utils
                    s = multihost_utils.process_allgather(s, tiled=True)
                    d = multihost_utils.process_allgather(d, tiled=True)
                    fd = multihost_utils.process_allgather(fd,
                                                           tiled=True)
                if shard_walls is not None:
                    from ..parallel.mesh import record_sharded_dispatch
                    record_sharded_dispatch(
                        self.mesh, 'data', ln, padded_rows, shard_walls,
                        time.perf_counter() - t_coll)
            with devtel.d2h_guard({'chunk_start': start,
                                   'rows': ln}) as g:
                s, d, fd = (np.array(s)[:ln], np.array(d)[:ln],
                            np.array(fd)[:ln])
                g.add_d2h_bytes(s.nbytes + d.nbytes + fd.nbytes)
            if self.mesh is None:
                self._free_inputs(t, out)
            cm = p['cm']
            release_chunk(p)
            return start, s, d, fd, None, cm

        if n <= chunk:
            # single-chunk fast path: pipeline thread spawn/join costs
            # more than it hides for one chunk (admission latency
            # floor).  The chunk span closes BEFORE the yield — holding
            # it across a yield would leak the current-span contextvar
            # into the consumer
            with devtel.install_capture(tel_capture), \
                    tracing.tracer().start_span(
                        'kyverno/device/chunk', {'chunk_start': 0},
                        parent=tel_parent):
                p = None
                try:
                    with tlmod.exec_scope(timeline, 0, 'encode'):
                        p = stage_encode(0)
                    with tlmod.exec_scope(timeline, 0, 'h2d'):
                        p = stage_h2d(p)
                    with tlmod.exec_scope(timeline, 0, 'device_eval'):
                        p = stage_eval(p)
                    with tlmod.exec_scope(timeline, 0, 'd2h'):
                        result = stage_d2h(p)
                except BaseException:
                    # the inline path has no pipeline cleanup hook: a
                    # stage crash must still hand the chunk's encode
                    # buffers back before the error surfaces
                    release_chunk(p)
                    raise
            yield result
            return

        pipe = ChunkPipeline(
            [('encode', stage_encode), ('h2d', stage_h2d),
             ('device_eval', stage_eval), ('d2h', stage_d2h)],
            capture=tel_capture, parent_span=tel_parent,
            cleanup=release_chunk, timeline=timeline)
        yield from pipe.run(range(0, n, chunk))

    def _partitioned_status_chunks(self, resources: List[dict],
                                   contexts: Optional[List[dict]] = None,
                                   match: Optional[np.ndarray] = None,
                                   match_fn=None, timeline=None):
        """Partitioned twin of ``_device_status_chunks``: each chunk
        encodes and dispatches once per partition runtime (the
        partition's own slot vocabulary, match plane and executable),
        then the composer scatters the per-partition buffers back into
        whole-set ``(status, detail, fdet)`` — the yield contract is
        identical, so assembly downstream never knows partitions exist.

        Differences from the monolithic path, all deliberate:

        * no forked encode pool and no :class:`LaneArena` — both are
          bound to the whole-set ``cps`` vocabulary, and per-partition
          lane sets are smaller (the arena would fragment across
          heterogeneous vocabularies);
        * no in-graph admission output — ``self._adm`` is None in
          partitioned mode, so admission rows were already decided
          exactly by the host matcher (the yielded ``adm`` is None);
        * per-partition evaluators dispatch serially within a chunk
          (one accelerator; the chunk pipeline still overlaps encode /
          h2d / eval / d2h across chunks)."""
        n = len(resources)
        from ..observability import device as devtel
        from ..observability import timeline as tlmod
        from ..observability import tracing
        from ..ops.eval import (expand_compact, fold_match_unique,
                                shard_batch)
        from .pipeline import ChunkPipeline
        chunk = self.CHUNK
        small = self.mesh is None and n <= self.SMALL_BATCH
        device = self._small_device() if small else None
        tel_parent = tracing.current_span()
        tel_capture = devtel.current_capture()
        rts = self._pset.runtimes

        def stage_encode(start):
            faults.check(faults.SITE_ENCODE)
            part = resources[start:start + chunk]
            part_ctx = contexts[start:start + chunk] \
                if contexts is not None else None
            cm = match[start:start + len(part)] if match is not None \
                else (match_fn(start, part) if match_fn is not None
                      else None)
            bucket = chunk if n > chunk else canonical_capacity(
                len(part), chunk=chunk, small=self.SMALL_BATCH)
            encs = []
            with devtel.stage('encode', {'rows': len(part),
                                         'partitions': len(rts)}):
                for rt in rts:
                    batch = encode_batch(part, rt.sub_cps,
                                         padded_n=bucket,
                                         contexts=part_ctx)
                    encs.append(batch.tensors())
            return {'start': start, 'ln': len(part), 'bucket': bucket,
                    'encs': encs, 'cm': cm}

        def stage_h2d(p):
            faults.check(faults.SITE_H2D)
            ln = p['ln']
            devtel.set_batch_size(ln)
            cm = p['cm']
            dev_m = (cm & self._dev_mask).astype(np.uint8) \
                if cm is not None else None
            shipped = []
            for rt, tensors in zip(rts, p['encs']):
                padded = next(iter(tensors.values())).shape[0]
                tensors = dict(tensors)
                if dev_m is not None:
                    # slice the global device-mask'd match down to this
                    # partition's program columns, then fold to ITS
                    # unique space — each executable sees exactly the
                    # plane the monolithic path would have shown for
                    # those columns
                    mm_u = fold_match_unique(
                        np.ascontiguousarray(dev_m[:, rt.prog_cols]),
                        rt.evaluator)
                    mm = np.zeros((padded, mm_u.shape[1]), np.uint8)
                    mm[:ln] = mm_u
                    tensors['__match__'] = mm
                if rt.adm is not None:
                    # zero lanes keep the executable signature stable
                    # (the in-graph decision is discarded; the host
                    # matcher already decided admission rows)
                    tensors.update(admission_lanes.zero_lanes(
                        rt.adm, padded))
                shipped.append(shard_batch(tensors, None,
                                           device=device))
            p['encs'] = None
            p['shipped'] = shipped
            return p

        def stage_eval(p):
            faults.check(faults.SITE_DEVICE_EVAL)
            p['outs'] = [rt.evaluator(t, layout)
                         for rt, (t, layout) in zip(rts, p['shipped'])]
            return p

        def stage_d2h(p):
            faults.check(faults.SITE_D2H)
            start, ln = p['start'], p['ln']
            parts_out = []
            with devtel.d2h_guard({'chunk_start': start,
                                   'rows': ln}) as g:
                for rt, (t, _layout), out in zip(rts, p['shipped'],
                                                 p['outs']):
                    if len(out) == 2:
                        o8 = np.array(out[0])
                        o32 = np.array(out[1])
                        g.add_d2h_bytes(o8.nbytes + o32.nbytes)
                        s_k, d_k, fd_k, _adm = expand_compact(
                            o8, o32, rt.evaluator)
                    else:
                        s_k, d_k, fd_k = (np.array(out[0]),
                                          np.array(out[1]),
                                          np.array(out[2]))
                        g.add_d2h_bytes(s_k.nbytes + d_k.nbytes +
                                        fd_k.nbytes)
                    self._free_inputs(t, out)
                    parts_out.append((s_k[:ln], d_k[:ln], fd_k[:ln]))
            p['shipped'] = p['outs'] = None
            s, d, fd = self._composer.compose(parts_out, ln)
            return start, s, d, fd, None, p['cm']

        if n <= chunk:
            with devtel.install_capture(tel_capture), \
                    tracing.tracer().start_span(
                        'kyverno/device/chunk', {'chunk_start': 0},
                        parent=tel_parent):
                with tlmod.exec_scope(timeline, 0, 'encode'):
                    p = stage_encode(0)
                with tlmod.exec_scope(timeline, 0, 'h2d'):
                    p = stage_h2d(p)
                with tlmod.exec_scope(timeline, 0, 'device_eval'):
                    p = stage_eval(p)
                with tlmod.exec_scope(timeline, 0, 'd2h'):
                    result = stage_d2h(p)
            yield result
            return

        pipe = ChunkPipeline(
            [('encode', stage_encode), ('h2d', stage_h2d),
             ('device_eval', stage_eval), ('d2h', stage_d2h)],
            capture=tel_capture, parent_span=tel_parent,
            timeline=timeline)
        yield from pipe.run(range(0, n, chunk))

    def _device_statuses(self, resources: List[dict],
                         contexts: Optional[List[dict]] = None,
                         match: Optional[np.ndarray] = None):
        parts = list(self._device_status_chunks(resources, contexts, match))
        if len(parts) == 1:
            return parts[0][1:4]
        return tuple(np.concatenate([p[i] for p in parts])
                     for i in range(1, 4))

    def scan_statuses(self, resources: List[dict]):
        """Raw (status, detail, match) matrices over all compiled programs
        — the allocation-free fast path for throughput measurement and
        report aggregation."""
        wrapped = [Resource(r) for r in resources]
        match = self.match_matrix(resources, wrapped)
        status, detail, _ = self._device_statuses(resources, match=match)
        return status, detail, match

    # -- full responses -----------------------------------------------------

    def scan(self, resources: List[dict],
             contexts: Optional[List[dict]] = None,
             admission: Optional[tuple] = None,
             pctx_factory=None,
             old_resources: Optional[List[Optional[dict]]] = None,
             admissions: Optional[List[Optional[tuple]]] = None
             ) -> List[List[EngineResponse]]:
        """Return, per resource, the engine responses of all policies with
        at least one applicable rule (host-identical).

        Webhook scans pass ``contexts`` (the admission JSON context per
        resource), ``admission`` (admission_info, exclude_group_roles,
        namespace_labels, operation) for match semantics, and
        ``pctx_factory(doc)`` so host materialization sees the same
        PolicyContext the engine loop would build.  Heterogeneous
        batches pass ``admissions`` — one admission tuple PER ROW —
        instead: rules whose match depends on the tuple are decided
        in-graph from per-row admission lanes when the policy set
        lowered them (compiler/admission.py), per-row on the host
        otherwise.  UPDATE-verb rows additionally carry their
        ``oldObject`` in ``old_resources`` (row-aligned, None for rows
        without one): the engine retries a failed new-object match
        against the old object, so the host match sieve must too —
        evaluation itself stays on the new object, exactly like the
        engine."""
        return list(self.scan_stream(resources, contexts, admission,
                                     pctx_factory, old_resources,
                                     admissions))

    def scan_stream(self, resources: List[dict],
                    contexts: Optional[List[dict]] = None,
                    admission: Optional[tuple] = None,
                    pctx_factory=None,
                    old_resources: Optional[List[Optional[dict]]] = None,
                    admissions: Optional[List[Optional[tuple]]] = None):
        """Generator form of ``scan``: yields each resource's responses
        in order as its device chunk completes.  Consumers that do
        per-resource work (report construction, CR writes) overlap it
        with the next chunk's encode/transfer/device stages instead of
        paying it serially after the whole scan."""
        if not resources:
            return
        yield from self._scan_inner(resources, contexts, admission,
                                    pctx_factory, old_resources,
                                    admissions)

    def _scan_inner(self, resources, contexts, admission, pctx_factory,
                    old_resources=None, admissions=None):
        n = len(resources)
        self._pctx_factory = pctx_factory
        # context-load outcomes are memoized within one scan pass only —
        # the host engine reloads per evaluation, so staleness must not
        # outlive a pass
        self._ctx_ok_cache = {}
        # admission scans evaluate every policy; the background gate
        # (engine.py:174 apply_background_checks) only applies to scans
        background_mode = admission is None and admissions is None and \
            pctx_factory is None
        wrapped = [Resource(r) for r in resources]
        adm_rows = admissions if admissions is not None else (
            [admission] * n if admission is not None else None)
        # per-row admission lanes: encode once per scan; rows whose
        # tuples do not intern exactly fall back to the host matcher
        # alone (taxonomy: admission_unencodable), never the batch
        plan = None
        if adm_rows is not None and self._adm is not None and \
                self.mesh is None:
            old_flags = [bool(o) for o in old_resources] \
                if old_resources is not None else None
            plan = admission_lanes.encode_rows(self._adm, adm_rows,
                                               old_flags)
            atoms = self._adm_res_atoms(resources, wrapped)
            plan.lanes['__admres__'] = atoms
            plan.upper = admission_lanes.match_upper(self._adm, atoms)
            bad = int(plan.unencodable.sum())
            if bad:
                coverage.record_fallback(
                    'validate', coverage.REASON_ADMISSION_UNENCODABLE,
                    rows=bad)
        match = self.match_matrix(resources, wrapped, adm_rows=adm_rows,
                                  plan=plan)
        if old_resources is not None and any(old_resources):
            match = self._fold_old_matches(match, wrapped, adm_rows,
                                           old_resources)
        now = time.time()
        ts = int(now)

        # which host policies could match each resource at all (group
        # screen over their simple rules; non-simple rules force a run).
        # The screen is valid for admission scans too: simple-match
        # rules only reference kinds/namespaces (the matcher ignores
        # operations entirely, and roles/subjects rules are non-simple),
        # and a screened-out policy contributes the same empty response
        # the engine would produce.
        host_maybe = self._host_policy_maybe(resources, wrapped,
                                             old_resources)

        progs = self.cps.programs
        background_ok = getattr(self, '_background_ok', None)
        if background_ok is None:
            background_ok = self._background_ok = np.array([
                self.policies[p.policy_index].background for p in progs])

        # the device chunks stream through while this loop assembles —
        # three pipeline stages (encode / device / assemble) overlap;
        # assembly strategy details live in _assemble_chunk.
        # each span covers one chunk's device wait + host assembly and
        # opens/closes within a single generator step (no yield inside
        # the with-block): holding one span across yields would leak the
        # current-span contextvar into the consumer and record a bogus
        # error when the consumer stops iterating early
        from ..observability import timeline as tlmod
        from ..observability import tracing
        tl = tlmod.begin_scan()
        chunk_cap = max(self.CHUNK, 1)
        chunks = self._device_status_chunks(resources, contexts, match,
                                            adm_plan=plan, timeline=tl)
        tally = coverage.scan_tally()
        start = 0
        try:
            while start < n:
                with tracing.start_span(
                        'kyverno/device/scan',
                        {'chunk_start': start,
                         'programs': len(progs)}) as span:
                    try:
                        start, status, detail, fdet, adm_out, _cm = \
                            next(chunks)
                    except StopIteration:
                        return
                    if adm_out is not None and plan is not None:
                        # the exact in-graph admission-match decision
                        # replaces the conservative upper bound for
                        # device-valid rows before assembly reads it
                        vr = np.flatnonzero(
                            plan.valid[start:start + status.shape[0]])
                        if vr.size:
                            match[np.ix_(start + vr, self._adm_cols)] = \
                                adm_out[vr].astype(bool)
                    span.set_attribute('resources', status.shape[0])
                    from ..observability import device as devtel
                    t_rep = time.monotonic() if tl is not None else 0.0
                    with devtel.stage('report',
                                      {'rows': status.shape[0]}) as rstage:
                        chunk_rows = self._assemble_chunk(
                            resources, wrapped, match, start, status,
                            detail, fdet, now, ts, background_mode,
                            background_ok, host_maybe, tally)
                        if tally is not None:
                            ratio = tally.ratio()
                            if ratio is not None:
                                # cumulative within this scan — the
                                # fallback-attribution view of the chunk
                                rstage.set_attribute(
                                    'device_coverage_ratio',
                                    round(ratio, 4))
                                span.set_attribute(
                                    'device_coverage_ratio',
                                    round(ratio, 4))
                    if tl is not None:
                        tl.record('report', start // chunk_cap, t_rep)
                start += status.shape[0]
                yield from chunk_rows
        finally:
            # flush even when the consumer abandons the stream early —
            # partial scans still land in the ledger and set the
            # per-scan coverage-ratio gauge
            if tally is not None:
                tally.finish()
                from ..observability import device as devtel
                cap = devtel.current_capture()
                if cap is not None:
                    cap.coverage_ratio = tally.ratio()
            # tear the pipeline down BEFORE finalizing the timeline:
            # close_open/drain must have run so the blame walk sees
            # every interval closed (deterministic on early close too)
            chunks.close()
            tlmod.finish_scan(tl)

    def _assemble_chunk(self, resources, wrapped, match, start, status,
                        detail, fdet, now, ts, background_mode,
                        background_ok, host_maybe, tally=None
                        ) -> List[List[EngineResponse]]:
        """Assemble one device chunk into per-resource engine responses.

        Large chunks assemble column-wise (per program over the whole
        chunk): the status branch, message lookup and int casts
        amortize over all rows of a column.  Small batches (admission:
        one resource) assemble row-wise — a column sweep would pay one
        numpy call per program for a single resource.  Identical
        device-synthesized cells share one flyweight RuleResponse
        (treat rule responses from scan() as immutable — every
        downstream consumer only reads)."""
        _HOST = _HOST_MARKER
        progs = self.cps.programs
        m = status.shape[0]
        sub_match = match[start:start + m]
        # per-row [(policy_index, RuleResponse|None), ...] in j order
        acc: List[list] = [[] for _ in range(m)]
        fly: Dict[Tuple, Any] = {}
        if m <= self.SMALL_BATCH:
            for k in range(m):
                row_js = np.flatnonzero(sub_match[k] & self._dev_mask)
                st_row = status[k]
                det_row = detail[k]
                for j in row_js.tolist():
                    prog = progs[j]
                    if background_mode and not background_ok[j]:
                        acc[k].append((prog.policy_index, None))
                        continue
                    rr = self._cell(prog, j, int(st_row[j]),
                                    int(det_row[j]), fdet[k], ts, fly,
                                    resources[start + k], tally)
                    if rr is _HOST:
                        rr = self._materialize(prog,
                                               resources[start + k])
                        if rr is not None:
                            rr.timestamp = ts
                    acc[k].append((prog.policy_index,
                                   None if rr is None or rr is _HOST
                                   else rr))
        else:
            for j, prog in self.device_programs:
                rows = np.flatnonzero(sub_match[:, j])
                if rows.size == 0:
                    continue
                p_idx = prog.policy_index
                if background_mode and not background_ok[j]:
                    # background-disabled policies contribute an empty
                    # response (engine.py:174 apply_background_checks)
                    for k in rows.tolist():
                        acc[k].append((p_idx, None))
                    continue
                st_col = status[rows, j].tolist()
                det_col = detail[rows, j].tolist()
                for k, st, det in zip(rows.tolist(), st_col, det_col):
                    rr = self._cell(prog, j, st, det, fdet[k], ts, fly,
                                    resources[start + k], tally)
                    if rr is _HOST:
                        # anchor-SKIP / HOST / unsynthesizable FAIL:
                        # re-run on the host for exact status+message
                        rr = self._materialize(prog,
                                               resources[start + k])
                        if rr is not None:
                            rr.timestamp = ts
                    acc[k].append((p_idx, None if rr is None or
                                   rr is _HOST else rr))
        chunk_rows: List[List[EngineResponse]] = []
        for k in range(m):
            i = start + k
            res_doc = resources[i]
            responses: Dict[int, EngineResponse] = {}
            for p_idx, rr in acc[k]:
                resp = responses.get(p_idx)
                if resp is None:
                    resp = self._new_response(p_idx, res_doc, now,
                                              wrapped[i])
                    responses[p_idx] = resp
                if rr is None:
                    continue
                pr = resp.policy_response
                pr.rules.append(rr)
                st = rr.status
                if st == RuleStatus.PASS or st == RuleStatus.FAIL:
                    pr.rules_applied_count += 1
                elif st == RuleStatus.ERROR:
                    pr.rules_error_count += 1
            for p_idx in self._host_policy_idx:
                if background_mode and not self._policy_header[p_idx][0].background:
                    # background-disabled policy: empty response without
                    # a host-engine round trip (engine.py:174
                    # apply_background_checks short-circuit)
                    responses[p_idx] = self._new_response(
                        p_idx, res_doc, now, wrapped[i])
                elif host_maybe[p_idx] is None or host_maybe[p_idx][i]:
                    responses[p_idx] = self._host_run(p_idx, res_doc)
                    if tally is not None:
                        self._tally_host_policy(tally, p_idx,
                                                responses[p_idx])
                else:
                    responses[p_idx] = self._new_response(
                        p_idx, res_doc, now, wrapped[i])
            chunk_rows.append([responses[q] for q in sorted(responses)])
        return chunk_rows

    def _tally_host_policy(self, tally, p_idx: int, resp) -> None:
        """Attribute every rule response of a whole-policy host run to
        its compile-time fallback reason (policy_coupling for rules that
        compiled but ride host with their policy)."""
        pol = self._policy_header[p_idx][1]
        for rr in resp.policy_response.rules:
            reason, path = self._host_rule_reason.get(
                (pol, rr.name),
                (coverage.REASON_POLICY_COUPLING, 'validate'))
            tally.host_rule(pol, rr.name, reason, path)

    #: rows per incremental report-assembly window: each device chunk
    #: assembles (and yields) in sub-windows of at most this many rows,
    #: so the resident decoded-result footprint is bounded by the knob,
    #: not the chunk capacity
    REPORT_FLUSH_ROWS = int(__import__('os').environ.get(
        'KTPU_REPORT_FLUSH_ROWS', '8192'))

    def _report_order(self):
        """Device programs in report-result sort order with their static
        report fields: ``(j, prog, p_idx, policy_key, scored, category,
        severity)``.  Report results sort on (policy key, rule name,
        0, (), ts) and one scan shares one ts, so emitting columns in
        this precomputed order yields each row's results already sorted
        — no per-row sort on the streaming path (stable order matches
        the unfused path's stable sort)."""
        cached = getattr(self, '_report_order_cache', None)
        if cached is None:
            from ..reports.results import _policy_static
            entries = []
            for j, prog in self.device_programs:
                policy = self.policies[prog.policy_index]
                key, scored, category, severity = _policy_static(policy)
                entries.append((key, prog.rule_name, j, prog,
                                prog.policy_index, scored, category,
                                severity))
            entries.sort(key=lambda e: (e[0], e[1]))
            cached = self._report_order_cache = [
                (j, prog, p_idx, key, scored, category, severity)
                for key, _rn, j, prog, p_idx, scored, category, severity
                in entries]
        return cached

    _SUMMARY_BUCKETS = ('pass', 'fail', 'warn', 'error', 'skip')
    _BUCKET_IDX = {b: i for i, b in enumerate(_SUMMARY_BUCKETS)}

    def _assemble_report_window(self, resources, base, m, status, detail,
                                fdet, sub_match, background_ok, ts,
                                stamp, tally):
        """Columnar assembly of one chunk window: per ordered program
        column, group cells by (status, detail) and append the shared
        flyweight result dict to each matched row — one result-dict
        build per DISTINCT cell value, one numpy pass per column.
        Returns (rows, row_policies, counts, dirty) where ``counts`` is
        the [m, 5] summary matrix and ``dirty`` marks rows needing a
        sort-merge (host-policy rows)."""
        from ..reports.results import _rule_result
        rows: List[list] = [[] for _ in range(m)]
        row_pols: List[list] = [[] for _ in range(m)]
        counts = np.zeros((m, 5), np.int32)
        fly: Dict[Tuple, Any] = {}
        bucket_idx = self._BUCKET_IDX
        for j, prog, p_idx, key, scored, category, severity in \
                self._report_order():
            if not background_ok[j]:
                continue
            rows_j = np.flatnonzero(sub_match[:, j])
            if rows_j.size == 0:
                continue
            if tally is not None:
                tally.total_rows += int(rows_j.size)
            st_col = status[rows_j, j].astype(np.int32)
            det_col = detail[rows_j, j].astype(np.int32)
            # context-loading programs keep the per-cell path: the load
            # outcome depends on each resource's own context inputs
            per_cell = prog.context_spec is not None
            if per_cell:
                groups = [(None, None, rows_j)]
            else:
                combined = st_col * 1024 + (det_col + 512)
                uniq, inv = np.unique(combined, return_inverse=True)
                groups = [(int(u) // 1024 , int(u) % 1024 - 512,
                           rows_j[inv == gi])
                          for gi, u in enumerate(uniq)]
            for st, det, sub in groups:
                if per_cell:
                    # context programs check per resource: stay
                    # row-at-a-time (memoized on context inputs)
                    self._assemble_cells(
                        prog, j, p_idx, key, scored, category, severity,
                        sub, status, detail, fdet, resources, base, ts,
                        stamp, fly, rows, row_pols, counts, tally)
                    continue
                if st == STATUS_FAIL:
                    # FAIL messages hang off the per-row fail-detail
                    # buffer — but the relevant fdet columns take few
                    # distinct values, so group rows by them and
                    # synthesize one message per distinct detail
                    self._assemble_fail_groups(
                        prog, j, p_idx, key, scored, category, severity,
                        sub, fdet, resources, base, ts, stamp, fly,
                        rows, row_pols, counts, tally)
                    continue
                cell_key = (j, st, det)
                cell = fly.get(cell_key)
                if cell is None:
                    rr = self._synth_rule(prog, st, det, ts)
                    if rr is _HOST_MARKER:
                        cell = (_HOST_MARKER, 0)
                    else:
                        result = _rule_result(rr, key, scored, category,
                                              severity, stamp, ts)
                        cell = (result, bucket_idx[result['result']])
                    fly[cell_key] = cell
                result, bucket = cell
                if result is _HOST_MARKER:
                    if tally is not None:
                        tally.fallback_n(
                            prog, coverage.REASON_STATUS_HOST
                            if st == STATUS_HOST
                            else coverage.REASON_UNSYNTHESIZABLE,
                            int(sub.size))
                    for k in sub.tolist():
                        rr = self._materialize(prog, resources[base + k])
                        if rr is None:
                            continue
                        rr.timestamp = ts
                        res = _rule_result(rr, key, scored, category,
                                           severity, stamp, ts)
                        rows[k].append(res)
                        row_pols[k].append(p_idx)
                        counts[k, bucket_idx[res['result']]] += 1
                    continue
                if tally is not None:
                    tally.device_n(prog, int(sub.size))
                for k in sub.tolist():
                    rows[k].append(result)
                    row_pols[k].append(p_idx)
                counts[sub, bucket] += 1
        return rows, row_pols, counts

    def _assemble_fail_groups(self, prog, j, p_idx, key, scored,
                              category, severity, sub, fdet, resources,
                              base, ts, stamp, fly, rows, row_pols,
                              counts, tally):
        """Columnar FAIL assembly: rows group by the fail-detail
        columns the message synthesis actually reads (column j, or the
        anyPattern child block), one message per distinct detail."""
        from ..reports.results import _rule_result
        bucket_idx = self._BUCKET_IDX
        meta = self._evaluator.any_meta.get(j) \
            if prog.any_fail_sites is not None else None
        if meta is None:
            fds = fdet[sub, j]
            uf, inv = np.unique(fds, return_inverse=True)
            subgroups = [sub[inv == t] for t in range(uf.size)]
        else:
            p = len(self.cps.programs)
            block = fdet[sub, p + meta[0]:p + meta[0] + meta[1]]
            uf, inv = np.unique(block, axis=0, return_inverse=True)
            subgroups = [sub[inv == t] for t in range(uf.shape[0])]
        for sg in subgroups:
            msg = self._fail_message_cached(prog, j, fdet[sg[0]])
            if msg is None:
                if tally is not None:
                    tally.fallback_n(prog, coverage.REASON_UNSYNTHESIZABLE,
                                     int(sg.size))
                for k in sg.tolist():
                    rr = self._materialize(prog, resources[base + k])
                    if rr is None:
                        continue
                    rr.timestamp = ts
                    res = _rule_result(rr, key, scored, category,
                                       severity, stamp, ts)
                    rows[k].append(res)
                    row_pols[k].append(p_idx)
                    counts[k, bucket_idx[res['result']]] += 1
                continue
            cell_key = (j, STATUS_FAIL, msg)
            cell = fly.get(cell_key)
            if cell is None:
                rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                  msg, RuleStatus.FAIL)
                rr.timestamp = ts
                result = _rule_result(rr, key, scored, category,
                                      severity, stamp, ts)
                cell = (result, bucket_idx[result['result']])
                fly[cell_key] = cell
            result, bucket = cell
            if tally is not None:
                tally.device_n(prog, int(sg.size))
            for k in sg.tolist():
                rows[k].append(result)
                row_pols[k].append(p_idx)
            counts[sg, bucket] += 1

    def _assemble_cells(self, prog, j, p_idx, key, scored, category,
                        severity, sub, status, detail, fdet, resources,
                        base, ts, stamp, fly, rows, row_pols, counts,
                        tally):
        """Row-at-a-time assembly for the cells the columnar sweep
        cannot group: FAIL messages (per-row fail details) and
        context-loading programs (per-resource load outcomes)."""
        from ..reports.results import _rule_result
        bucket_idx = self._BUCKET_IDX
        _HOST = _HOST_MARKER
        for k in sub.tolist():
            rr = self._cell(prog, j, int(status[k, j]), int(detail[k, j]),
                            fdet[k], ts, fly, resources[base + k], tally)
            if rr is _HOST:
                rr = self._materialize(prog, resources[base + k])
                if rr is not None:
                    rr.timestamp = ts
            if rr is None or rr is _HOST:
                continue
            result = _rule_result(rr, key, scored, category, severity,
                                  stamp, ts)
            rows[k].append(result)
            row_pols[k].append(p_idx)
            counts[k, bucket_idx[result['result']]] += 1
        # _cell already incremented total_rows per cell — undo the
        # double count from the column-level bulk add
        if tally is not None:
            tally.total_rows -= int(sub.size)

    def scan_report_results(self, resources: List[dict],
                            now: Optional[float] = None):
        """Yield ``(results, summary, policies)`` per resource — the
        report-path fusion of ``scan_stream``: report-result dicts are
        built straight from the shared device-cell flyweights, skipping
        the per-(resource, policy) EngineResponse objects entirely
        (reference scanner.go:60 only ever turns EngineResponses into
        report results; bit-identity with the unfused path is pinned by
        tests/test_report_fusion.py).

        Fully streaming: the per-chunk match mask is computed inside
        the pipeline's encode stage (``match_fn``), verdict buffers are
        consumed chunk-by-chunk as each d2h lands, and rows assemble
        column-wise in ``KTPU_REPORT_FLUSH_ROWS`` windows — nothing is
        ever materialized at ``n_resources`` scale.

        ``results`` are shared flyweight dicts (never mutate);
        ``policies`` is the list of Policy objects contributing at least
        one rule (for report policy labels)."""
        from ..reports.results import engine_response_to_report_results
        if not resources:
            return
        n = len(resources)
        now = time.time() if now is None else now
        ts = int(now)
        ts_key = str(ts)
        stamp = {'seconds': ts}
        self._ctx_ok_cache = {}
        progs = self.cps.programs
        background_ok = getattr(self, '_background_ok', None)
        if background_ok is None:
            background_ok = self._background_ok = np.array([
                self.policies[p.policy_index].background for p in progs])

        def match_fn(start, part):
            # runs inside the pipeline's encode stage: the full [R, P]
            # mask and Resource list never exist
            return self.match_matrix(part, [Resource(r) for r in part])

        from ..observability import timeline as tlmod
        tl = tlmod.begin_scan()
        chunk_cap = max(self.CHUNK, 1)
        chunks = self._device_status_chunks(resources, None,
                                            match_fn=match_fn,
                                            timeline=tl)
        tally = coverage.scan_tally()
        flush = max(1, self.REPORT_FLUSH_ROWS)
        host_idx = [p_idx for p_idx in self._host_policy_idx
                    if self._policy_header[p_idx][0].background]
        done = 0
        try:
            while done < n:
                try:
                    start, status, detail, fdet, _adm, cm = next(chunks)
                except StopIteration:
                    return
                m = status.shape[0]
                host_maybe = None
                part_docs = resources[start:start + m]
                if host_idx:
                    part_wrapped = [Resource(r) for r in part_docs]
                    host_maybe = self._host_policy_maybe(part_docs,
                                                         part_wrapped)
                from ..observability import device as devtel
                for w0 in range(0, m, flush):
                    w1 = min(w0 + flush, m)
                    wm = w1 - w0
                    t_rep = time.monotonic() if tl is not None else 0.0
                    with devtel.stage('report', {'rows': wm}) as rstage:
                        rows, row_pols, counts = \
                            self._assemble_report_window(
                                resources, start + w0, wm,
                                status[w0:w1], detail[w0:w1],
                                fdet[w0:w1], cm[w0:w1], background_ok,
                                ts, stamp, tally)
                        if tally is not None:
                            ratio = tally.ratio()
                            if ratio is not None:
                                rstage.set_attribute(
                                    'device_coverage_ratio',
                                    round(ratio, 4))
                    if tl is not None:
                        tl.record('report', start // chunk_cap, t_rep)
                    for k in range(wm):
                        i = start + w0 + k
                        results = rows[k]
                        pols = row_pols[k]
                        dirty = False
                        for p_idx in host_idx:
                            if host_maybe[p_idx] is not None and \
                                    not host_maybe[p_idx][w0 + k]:
                                continue
                            resp = self._host_run(p_idx, resources[i])
                            if tally is not None:
                                self._tally_host_policy(tally, p_idx,
                                                        resp)
                            if not resp.policy_response.rules:
                                continue
                            pols.append(p_idx)
                            dirty = True
                            for result in \
                                    engine_response_to_report_results(
                                        resp, now=ts):
                                results.append(result)
                                counts[k, self._BUCKET_IDX[
                                    result['result']]] += 1
                        if dirty:
                            # host-policy results interleave by sort
                            # key; device results arrived pre-sorted,
                            # so only these rows pay a sort-merge
                            results.sort(key=lambda r: (
                                r.get('policy', ''), r.get('rule', ''),
                                0, (), ts_key))
                        c = counts[k]
                        summary = {
                            'pass': int(c[0]), 'fail': int(c[1]),
                            'warn': int(c[2]), 'error': int(c[3]),
                            'skip': int(c[4])}
                        seen: Dict[int, None] = dict.fromkeys(pols)
                        yield (results, summary,
                               [self.policies[p] for p in sorted(seen)])
                done += m
        finally:
            if tally is not None:
                tally.finish()
                from ..observability import device as devtel
                cap = devtel.current_capture()
                if cap is not None:
                    cap.coverage_ratio = tally.ratio()
            # pipeline teardown first (close_open/drain), then the
            # blame walk — see _scan_inner
            chunks.close()
            tlmod.finish_scan(tl)

    def _cell(self, prog, j: int, st: int, det: int, fdet_row, ts: int,
              fly: Dict[Tuple, Any], resource: Optional[dict] = None,
              tally=None):
        """Flyweight RuleResponse for one device cell (or _HOST_MARKER).

        FAIL cells key on the synthesized message — the fail-site detail
        row carries anyPattern metadata beyond column j and
        ``_fail_message_cached`` is itself memoized on the relevant
        columns.  ``tally`` (coverage.ScanTally or None) attributes
        every host decision: each branch that returns _HOST_MARKER must
        name its reason, so no fallback is ever silent."""
        if tally is not None:
            tally.total_rows += 1
        if prog.context_spec is not None and resource is not None and \
                not self._context_ok(prog, resource):
            # load failure must surface the host's exact error response
            if tally is not None:
                tally.fallback(prog, coverage.REASON_CONTEXT_LOAD)
            return _HOST_MARKER
        if st == STATUS_FAIL:
            msg = self._fail_message_cached(prog, j, fdet_row)
            if msg is None:
                if tally is not None:
                    tally.fallback(prog, coverage.REASON_UNSYNTHESIZABLE)
                return _HOST_MARKER
            key = (j, STATUS_FAIL, msg)
            rr = fly.get(key)
            if rr is None:
                rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                  msg, RuleStatus.FAIL)
                rr.timestamp = ts
                fly[key] = rr
            if tally is not None:
                tally.device(prog)
            return rr
        key = (j, st, det)
        rr = fly.get(key)
        if rr is None:
            rr = self._synth_rule(prog, st, det, ts)
            fly[key] = rr
        if tally is not None:
            if rr is _HOST_MARKER:
                tally.fallback(
                    prog, coverage.REASON_STATUS_HOST
                    if st == STATUS_HOST
                    else coverage.REASON_UNSYNTHESIZABLE)
            else:
                tally.device(prog)
        return rr

    def _synth_rule(self, prog, st: int, det: int, ts: int):
        """Build the shared (flyweight) RuleResponse for one device-
        synthesizable non-FAIL (program, status, detail) cell, or the
        _HOST_MARKER when the cell needs host materialization."""
        if st == STATUS_PASS:
            rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                              prog.pass_messages[det], RuleStatus.PASS)
            if prog.pss is not None:
                rr.pod_security_checks = {
                    'level': prog.pss[0], 'version': prog.pss[1],
                    'checks': []}
        elif st == STATUS_SKIP_PRECOND:
            rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                              PRECONDITIONS_SKIP_MESSAGE, RuleStatus.SKIP)
        elif st == STATUS_VAR_ERR:
            rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                              prog.error_messages[det], RuleStatus.ERROR)
        elif st == STATUS_SKIP and prog.skip_message is not None:
            # foreach 'rule skipped' is a static message
            rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                              prog.skip_message, RuleStatus.SKIP)
        else:
            # ktpu: noqa[KTPU302] -- the sole caller (_cell) attributes
            # status_host / unsynthesizable_message on its tally
            return _HOST_MARKER
        rr.timestamp = ts
        return rr

    def _host_policy_rules(self):
        """Per host policy: its autogen-expanded Rule objects when every
        rule is simple-match, else None (always run).  Autogen expansion
        deep-copies rule trees, so computing it per scan call dominated
        single-request admission latency — the policy set is immutable
        for a scanner's lifetime, compute once."""
        cached = getattr(self, '_host_rules_cache', None)
        if cached is None:
            from ..autogen.autogen import compute_rules
            cached = {}
            for p_idx in self._host_policy_idx:
                rules = compute_rules(self.policies[p_idx])
                cached[p_idx] = [Rule(r) for r in rules] \
                    if all(_rule_match_is_simple(r) for r in rules) else None
            self._host_rules_cache = cached
        return cached

    def _host_policy_maybe(self, resources, wrapped, old_resources=None):
        """Per host policy: bool[R] 'any rule may match', or None when the
        policy has non-simple rules (always run).  UPDATE rows OR in the
        old object's screen — the engine's old-match retry means a rule
        matching only the old object still runs, so screening it out
        would drop a response the engine would have produced (the screen
        may only over-approximate)."""
        maybe: Dict[int, Optional[np.ndarray]] = {}
        group_of = [_group_key(doc) for doc in resources]
        old_wrapped = {
            i: Resource(o) for i, o in enumerate(old_resources or [])
            if o}
        host_rules = self._host_policy_rules()
        for p_idx in self._host_policy_idx:
            policy = self.policies[p_idx]
            robj = host_rules[p_idx]
            if robj is None:
                maybe[p_idx] = None
                continue
            cache: Dict[Tuple, bool] = {}

            def screen(res, _policy=policy, _robj=robj):
                return self._policy_gate(_policy, res) and any(
                    matches_resource_description(
                        res, r, None, [], {}, '') is None
                    for r in _robj)

            flags = np.zeros(len(resources), bool)
            for i, key in enumerate(group_of):
                hit = cache.get(key)
                if hit is None:
                    hit = screen(wrapped[i])
                    cache[key] = hit
                if not hit and i in old_wrapped:
                    hit = screen(old_wrapped[i])
                flags[i] = hit
            maybe[p_idx] = flags
        return maybe

    @staticmethod
    def _site_path(sites: Tuple[str, ...], fd: int) -> Optional[str]:
        tmpl = sites[fd >> 16]
        if tmpl.startswith('\x00'):
            # DYNAMIC_SITE: the path embeds a per-resource resolved
            # wildcard key — host materialization produces the message
            return None
        if '{' in tmpl:
            tmpl = tmpl.replace('{e0}', str(fd & 0xFF)) \
                       .replace('{e1}', str((fd >> 8) & 0xFF))
        return tmpl

    def _fail_message_cached(self, prog: RuleProgram, j: int,
                             fdet_row) -> Optional[str]:
        """Memoized message synthesis: distinct (program, fail-detail)
        combinations are few, so scans hit the cache almost always."""
        meta = self._evaluator.any_meta.get(j) \
            if prog.any_fail_sites is not None else None
        if meta is not None:
            p = len(self.cps.programs)
            key = (j,) + tuple(
                int(x) for x in fdet_row[p + meta[0]:p + meta[0] + meta[1]])
        else:
            key = (j, int(fdet_row[j]))
        cache = self._fail_msg_cache
        if key in cache:
            return cache[key]
        v = self._fail_message(prog, j, fdet_row)
        if len(cache) > 65536:
            cache.clear()
        cache[key] = v
        return v

    def _fail_message(self, prog: RuleProgram, j: int,
                      fdet_row) -> Optional[str]:
        """Synthesize the exact host FAIL message from compile-time
        templates, or None when this FAIL needs host materialization.
        (reference formats: pkg/engine/validation.go:722 buildErrorMessage,
        validation.go:460 getDenyMessage, validation.go:746
        buildAnyPatternErrorMessage)."""
        if prog.any_fail_sites is not None:
            meta = self._evaluator.any_meta.get(j)
            if meta is None:
                return None
            base, n_children = meta
            p = len(self.cps.programs)
            parts = []
            for c in range(n_children):
                fd_c = int(fdet_row[p + base + c])
                if fd_c == -2:
                    continue  # skipped sub-pattern: omitted from message
                if fd_c < 0:
                    return None
                path = self._site_path(prog.any_fail_sites[c], fd_c)
                if path is None:
                    return None
                parts.append(f'rule {prog.rule_name}[{c}] failed at '
                             f'path {path}')
            if not parts or prog.any_fail_prefix is None:
                return None
            return prog.any_fail_prefix + ' '.join(parts)
        fd = int(fdet_row[j])
        if fd < 0:
            return None
        if prog.deny_fail_message is not None:
            return prog.deny_fail_message
        if prog.fail_prefix is None or prog.fail_sites is None:
            return None
        site = self._site_path(prog.fail_sites, fd)
        if site is None:
            return None
        return prog.fail_prefix + site

    def _pctx(self, policy: Policy, resource: dict) -> PolicyContext:
        factory = getattr(self, '_pctx_factory', None)
        if factory is not None:
            pctx = factory(resource)
            pctx = pctx.copy()
            pctx.policy = policy
            return pctx
        return PolicyContext(policy, new_resource=resource)

    def _context_ok(self, prog: RuleProgram, resource: dict) -> bool:
        """Attempt the rule's context loads the way the host engine
        would (reference: pkg/engine/jsonContext.go:126 LoadContext);
        False → the cell falls back to host materialization so the
        load-failure response is exact.  When the spec's variables are
        all request.object-rooted, outcomes memoize on their values —
        bulk scans then pay one load per distinct input combination."""
        cache_key = None
        if prog.context_inputs is not None:
            from ..engine.jmespath import search as jp_search
            doc_ctx = {'request': {'object': resource}}
            try:
                cache_key = (id(prog),) + tuple(
                    repr(jp_search(expr, doc_ctx))
                    for expr in prog.context_inputs)
            except Exception:  # noqa: BLE001 - unkeyable: just load
                cache_key = None
            if cache_key is not None:
                cache = getattr(self, '_ctx_ok_cache', None)
                if cache is None:
                    cache = self._ctx_ok_cache = {}
                hit = cache.get(cache_key)
                if hit is not None:
                    return hit
        pctx = self._pctx(self.policies[prog.policy_index], resource)
        ctx = pctx.json_context
        ctx.checkpoint()
        try:
            self.engine.context_loader.load(
                list(prog.context_spec), ctx,
                policy_name=prog.policy_name, rule_name=prog.rule_name)
            ok = True
        except Exception:  # noqa: BLE001 - exact failure via host path
            ok = False
        finally:
            ctx.restore()
        if cache_key is not None:
            if len(self._ctx_ok_cache) > 4096:
                self._ctx_ok_cache.clear()
            self._ctx_ok_cache[cache_key] = ok
        return ok

    def _materialize(self, prog: RuleProgram,
                     resource: dict) -> Optional[RuleResponse]:
        """Produce the exact host-engine rule response for one rule."""
        from ..engine.engine import Validator
        pctx = self._pctx(self.policies[prog.policy_index], resource)
        rule = Rule(prog.rule_raw or {})
        return Validator(self.engine, pctx, rule).validate()

    def _new_response(self, policy_index: int, resource: dict,
                      now: float,
                      wrapped: Optional[Resource] = None) -> EngineResponse:
        # template-dict fast path: the per-policy header fields are
        # static for the scanner's lifetime, and scans build one
        # response per (resource, policy) pair — instantiating via
        # __new__ + a C-level dict copy of a prebuilt template is ~4x
        # cheaper than copy.copy (which routes through __reduce_ex__)
        from ..engine.api import PolicyResponse
        templates = getattr(self, '_resp_templates', None)
        if templates is None:
            templates = self._resp_templates = {}
        tmpl = templates.get(policy_index)
        if tmpl is None:
            policy, name, namespace, vfa, vfa_overrides = \
                self._policy_header[policy_index]
            pr0 = PolicyResponse()
            pr0.policy_name = name
            pr0.policy_namespace = namespace
            pr0.validation_failure_action = vfa
            pr0.validation_failure_action_overrides = vfa_overrides
            tmpl = (policy, dict(pr0.__dict__))
            templates[policy_index] = tmpl
        policy, pr_dict = tmpl
        r = wrapped if wrapped is not None else Resource(resource)
        pr = PolicyResponse.__new__(PolicyResponse)
        d = dict(pr_dict)
        d['rules'] = []
        d['resource_name'] = r.name
        d['resource_namespace'] = r.namespace
        d['resource_kind'] = r.kind
        d['resource_api_version'] = r.api_version
        d['timestamp'] = int(now)
        pr.__dict__ = d
        resp = EngineResponse.__new__(EngineResponse)
        resp.__dict__ = {'policy': policy, 'patched_resource': resource,
                         'policy_response': pr, 'namespace_labels': {}}
        return resp

    def _host_run(self, policy_index: int, resource: dict) -> EngineResponse:
        policy = self.policies[policy_index]
        factory = getattr(self, '_pctx_factory', None)
        if factory is not None:
            pctx = self._pctx(policy, resource)
            return self.engine.validate(pctx)
        return self.engine.apply_background_checks(
            PolicyContext(policy, new_resource=resource))
