"""Batch scanner: the TPU-backed background-scan path.

This is the TPU-native replacement for the reference's per-resource scan
loop (reference: pkg/controllers/report/background/controller.go +
pkg/controllers/report/utils/scanner.go:60 ScanResource):

1. compile the policy set once (``compile_policies``)
2. project each resource onto the slot table (``encode_batch``)
3. run the jitted evaluator — a verdict sieve over [resources × rules]
4. synthesize responses for PASS verdicts from compile-time templates;
   re-materialize non-pass / host-fallback results with the host engine so
   messages and statuses are bit-identical to a pure host run

Match/exclude is precomputed host-side with a (kind, apiVersion, namespace)
cache, since most background-scan policies match on kinds alone.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from ..engine.api import EngineResponse, PolicyContext, RuleResponse, RuleStatus, RuleType
from ..engine.engine import Engine
from ..engine.match import matches_resource_description
from .compile import compile_policies
from .encode import encode_batch
from .ir import CompiledPolicySet, RuleProgram

STATUS_NAMES = {0: RuleStatus.PASS, 1: RuleStatus.FAIL, 2: RuleStatus.SKIP}

_SIMPLE_MATCH_KEYS = {'kinds', 'namespaces', 'operations'}


def _rule_match_is_simple(rule: dict) -> bool:
    """True when match/exclude depend only on kind/apiVersion/namespace."""
    def block_simple(block: dict) -> bool:
        for f in [block] + (block.get('any') or []) + (block.get('all') or []):
            res = f.get('resources') or {}
            if any(k not in _SIMPLE_MATCH_KEYS for k in res):
                return False
            if f.get('roles') or f.get('clusterRoles') or f.get('subjects'):
                return False
        return True
    return block_simple(rule.get('match') or {}) and \
        block_simple(rule.get('exclude') or {})


class BatchScanner:
    def __init__(self, policies: List[Policy], engine: Optional[Engine] = None,
                 mesh=None):
        self.policies = policies
        self.engine = engine or Engine()
        self.cps: CompiledPolicySet = compile_policies(policies)
        from ..ops.eval import build_evaluator
        self._evaluator = build_evaluator(self.cps)
        self.mesh = mesh
        self._match_cache: Dict[Tuple, bool] = {}
        self._simple_match = [
            _rule_match_is_simple(p.rule_raw or {}) for p in self.cps.programs]
        # policies that have at least one host-fallback rule
        self._host_policy_idx = sorted({i for i, _, _ in self.cps.host_rules})

    # -- match --------------------------------------------------------------

    def _matches(self, prog_idx: int, prog: RuleProgram,
                 resource: Resource) -> bool:
        rule = Rule(prog.rule_raw or {})
        policy = self.policies[prog.policy_index]
        if self._simple_match[prog_idx]:
            key = (prog.policy_index, prog.rule_index, resource.kind,
                   resource.api_version, resource.namespace)
            cached = self._match_cache.get(key)
            if cached is not None:
                return cached
            result = matches_resource_description(
                resource, rule, None, [], {}, policy.namespace) is None
            self._match_cache[key] = result
            return result
        return matches_resource_description(
            resource, rule, None, [], {}, policy.namespace) is None

    # -- scan ---------------------------------------------------------------

    def scan(self, resources: List[dict]) -> List[List[EngineResponse]]:
        """Return, per resource, the engine responses of all policies."""
        n = len(resources)
        if n == 0:
            return []
        wrapped = [Resource(r) for r in resources]

        status = self._device_statuses(resources)

        # match mask [R, P]
        match = np.zeros((n, len(self.cps.programs)), bool)
        for j, prog in enumerate(self.cps.programs):
            for i, res in enumerate(wrapped):
                match[i, j] = self._matches(j, prog, res)

        out: List[List[EngineResponse]] = []
        for i, res_doc in enumerate(resources):
            responses: Dict[int, EngineResponse] = {}
            needs_host: set = set(self._host_policy_idx)
            for j, prog in enumerate(self.cps.programs):
                if not match[i, j] or prog.policy_index in needs_host:
                    continue
                st = int(status[i, j])
                resp = responses.get(prog.policy_index)
                if resp is None:
                    resp = self._new_response(prog.policy_index, res_doc)
                    responses[prog.policy_index] = resp
                if st == 0:
                    rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                      prog.pass_message, RuleStatus.PASS)
                else:
                    # non-pass: materialize the exact message by re-walking
                    # just this rule's pattern (compiled rules are
                    # variable-free, so the walk is context-independent)
                    rr = self._materialize(prog, res_doc)
                resp.policy_response.rules.append(rr)
                if rr.status in (RuleStatus.PASS, RuleStatus.FAIL):
                    resp.policy_response.rules_applied_count += 1
                elif rr.status == RuleStatus.ERROR:
                    resp.policy_response.rules_error_count += 1
            for p_idx in needs_host:
                responses[p_idx] = self._host_run(p_idx, res_doc)
            out.append([responses[k] for k in sorted(responses)])
        return out

    def _materialize(self, prog: RuleProgram, resource: dict) -> RuleResponse:
        """Produce the exact host-engine rule response for one rule."""
        from ..engine.engine import Validator
        pctx = PolicyContext(self.policies[prog.policy_index],
                             new_resource=resource)
        rule = Rule(prog.rule_raw or {})
        return Validator(self.engine, pctx, rule).validate()

    def _device_statuses(self, resources: List[dict]) -> np.ndarray:
        if not self.cps.programs:
            return np.zeros((len(resources), 0), np.int8)
        n = len(resources)
        # bucketed padding: compile once per power-of-two bucket; padded
        # rows evaluate on zeroed (TAG_MISSING) slots and are sliced off
        bucket = max(64, 1 << (n - 1).bit_length())
        batch = encode_batch(resources, self.cps, padded_n=bucket)
        from ..ops.eval import shard_batch
        tensors = shard_batch(batch.tensors(), self.mesh)
        return np.asarray(self._evaluator(tensors))[:n]

    def _new_response(self, policy_index: int, resource: dict) -> EngineResponse:
        policy = self.policies[policy_index]
        resp = EngineResponse(policy, patched_resource=resource)
        pr = resp.policy_response
        pr.policy_name = policy.name
        pr.policy_namespace = policy.namespace
        r = Resource(resource)
        pr.resource_name = r.name
        pr.resource_namespace = r.namespace
        pr.resource_kind = r.kind
        pr.resource_api_version = r.api_version
        pr.validation_failure_action = policy.validation_failure_action
        pr.validation_failure_action_overrides = \
            policy.validation_failure_action_overrides
        return resp

    def _host_run(self, policy_index: int, resource: dict) -> EngineResponse:
        policy = self.policies[policy_index]
        pctx = PolicyContext(policy, new_resource=resource)
        return self.engine.apply_background_checks(pctx)
