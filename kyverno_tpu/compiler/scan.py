"""Batch scanner: the TPU-backed background-scan path.

This is the TPU-native replacement for the reference's per-resource scan
loop (reference: pkg/controllers/report/background/controller.go +
pkg/controllers/report/utils/scanner.go:60 ScanResource):

1. compile the policy set once (``compile_policies``)
2. project each resource onto the slot table (``encode_batch``)
3. run the jitted evaluator — a verdict sieve over [resources × rules]
4. synthesize responses for PASS / precondition-SKIP verdicts from
   compile-time templates; re-materialize FAIL / anchor-SKIP / HOST
   results with the host engine so messages and statuses are always
   bit-identical to a pure host run

Match/exclude is evaluated once per (kind, apiVersion, namespace) group
for rules whose match blocks only reference those fields — the common
case for background-scan policies — instead of once per (resource, rule)
pair (reference match semantics: pkg/engine/utils.go:185).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from ..engine.api import (EngineResponse, PolicyContext, RuleResponse,
                          RuleStatus, RuleType)
from ..engine.engine import Engine
from ..engine.match import matches_resource_description
from .compile import compile_policies
from .encode import encode_batch
from .ir import (STATUS_FAIL, STATUS_HOST, STATUS_PASS, STATUS_SKIP,
                 STATUS_SKIP_PRECOND, STATUS_VAR_ERR, CompiledPolicySet,
                 RuleProgram)

_SIMPLE_MATCH_KEYS = {'kinds', 'namespaces', 'operations'}

PRECONDITIONS_SKIP_MESSAGE = 'preconditions not met'


def _rule_match_is_simple(rule: dict) -> bool:
    """True when match/exclude depend only on kind/apiVersion/namespace."""
    def block_simple(block: dict) -> bool:
        for f in [block] + (block.get('any') or []) + (block.get('all') or []):
            res = f.get('resources') or {}
            if any(k not in _SIMPLE_MATCH_KEYS for k in res):
                return False
            if f.get('roles') or f.get('clusterRoles') or f.get('subjects'):
                return False
        return True
    return block_simple(rule.get('match') or {}) and \
        block_simple(rule.get('exclude') or {})


def _group_key(doc: dict) -> Tuple[str, str, str]:
    meta = doc.get('metadata') or {}
    return (str(doc.get('kind', '')), str(doc.get('apiVersion', '')),
            str(meta.get('namespace', '') or ''))


class BatchScanner:
    """Compiles a policy set once and evaluates resource batches on device.

    ``scan`` returns the full per-resource engine responses (bit-identical
    to the host engine); ``scan_statuses`` returns just the raw device
    verdict matrices for throughput-critical callers.
    """

    def __init__(self, policies: List[Policy], engine: Optional[Engine] = None,
                 mesh=None):
        self.policies = policies
        self.engine = engine or Engine()
        self.cps: CompiledPolicySet = compile_policies(policies)
        self.mesh = mesh
        # policies needing the host engine for at least one rule, plus
        # applyRules=One policies (early-exit coupling between rules)
        self._host_policy_idx = sorted(
            {i for i, _, _ in self.cps.host_rules} |
            {i for i, p in enumerate(policies)
             if (p.apply_rules or 'All') == 'One'})
        host_set = set(self._host_policy_idx)
        # device-synthesizable programs (their whole policy compiled)
        self.device_programs: List[Tuple[int, RuleProgram]] = [
            (j, prog) for j, prog in enumerate(self.cps.programs)
            if prog.policy_index not in host_set]
        from ..ops.eval import build_evaluator
        self._evaluator = build_evaluator(self.cps)
        self._simple_match = [
            _rule_match_is_simple(p.rule_raw or {}) for p in self.cps.programs]
        self._match_cache: Dict[Tuple, np.ndarray] = {}
        self._rules = [Rule(p.rule_raw or {}) for p in self.cps.programs]

    # -- match --------------------------------------------------------------

    def _policy_gate(self, policy: Policy, res: Resource) -> bool:
        """Namespaced policies only apply inside their own namespace
        (engine.py:230-236, reference: pkg/engine/validation.go:117)."""
        if not policy.is_namespaced:
            return True
        return bool(res.namespace) and res.namespace == policy.namespace

    def _match_one(self, j: int, res: Resource,
                   admission: Optional[tuple] = None) -> bool:
        prog = self.cps.programs[j]
        policy = self.policies[prog.policy_index]
        if not self._policy_gate(policy, res):
            return False
        info, roles, ns_labels = admission or (None, [], {})
        return matches_resource_description(
            res, self._rules[j], info, roles, ns_labels, '') is None

    def match_matrix(self, resources: List[dict], wrapped: List[Resource],
                     admission: Optional[tuple] = None) -> np.ndarray:
        """[R, P] bool match mask, group-cached for simple-match rules.
        ``admission`` carries (admission_info, exclude_group_roles,
        namespace_labels, operation) for webhook scans; simple-match
        rules only reference kinds/namespaces/operations, so the group
        cache stays valid with the operation folded into the key."""
        n = len(resources)
        p = len(self.cps.programs)
        match = np.zeros((n, p), bool)
        if p == 0:
            return match
        simple = np.asarray(self._simple_match)
        operation = admission[3] if admission else ''
        adm3 = admission[:3] if admission else None
        # group resources by (kind, apiVersion, namespace, operation)
        groups: Dict[Tuple, List[int]] = {}
        for i, doc in enumerate(resources):
            groups.setdefault(_group_key(doc) + (operation,), []).append(i)
        for key, idxs in groups.items():
            cached = self._match_cache.get(key)
            if cached is None:
                rep = wrapped[idxs[0]]
                cached = np.array([
                    self._match_one(j, rep, adm3) if simple[j] else False
                    for j in range(p)])
                self._match_cache[key] = cached
            match[idxs, :] = cached
        # non-simple rules: evaluate per resource
        for j in np.nonzero(~simple)[0]:
            for i in range(n):
                match[i, j] = self._match_one(int(j), wrapped[i], adm3)
        return match

    # -- device evaluation --------------------------------------------------

    #: fixed device-chunk size: XLA compiles the evaluator once per
    #: distinct batch shape, so large scans stream fixed-size chunks
    CHUNK = int(__import__('os').environ.get('KTPU_SCAN_CHUNK', '8192'))
    #: batches at or below this size run on the host-local CPU backend:
    #: a single admission request must not pay a remote-accelerator
    #: round trip (latency floor), while bulk scans amortize it
    SMALL_BATCH = int(__import__('os').environ.get(
        'KTPU_SMALL_BATCH', '64'))

    def _small_device(self):
        import jax
        try:
            if jax.default_backend() != 'cpu':
                return jax.local_devices(backend='cpu')[0]
        except Exception:  # noqa: BLE001 - no cpu backend registered
            return None
        return None

    def _device_statuses(self, resources: List[dict],
                         contexts: Optional[List[dict]] = None):
        if not self.cps.programs or not resources:
            z = np.zeros((len(resources), len(self.cps.programs)), np.int8)
            return z, z
        from concurrent.futures import ThreadPoolExecutor
        from ..ops.eval import shard_batch
        n = len(resources)
        chunk = self.CHUNK
        small = self.mesh is None and n <= self.SMALL_BATCH
        device = self._small_device() if small else None

        def dispatch(tensors, ln):
            t, layout = shard_batch(tensors, self.mesh, device=device)
            s, d = self._evaluator(t, layout)
            return np.asarray(s)[:ln], np.asarray(d)[:ln]

        # depth-2 pipeline: the host encodes chunk i+1 while a dispatch
        # thread streams chunk i to the device and collects verdicts
        results: List = []
        with ThreadPoolExecutor(max_workers=1) as pool:
            futures = []
            for start in range(0, n, chunk):
                part = resources[start:start + chunk]
                part_ctx = contexts[start:start + chunk] \
                    if contexts is not None else None
                # bucketed padding: power-of-two buckets below one chunk,
                # exactly CHUNK otherwise → few compiled shapes total
                bucket = chunk if n > chunk else \
                    max(64, 1 << (len(part) - 1).bit_length())
                batch = encode_batch(part, self.cps, padded_n=bucket,
                                     contexts=part_ctx)
                futures.append(pool.submit(dispatch, batch.tensors(),
                                           len(part)))
                while len(futures) > 2:
                    results.append(futures.pop(0).result())
            for f in futures:
                results.append(f.result())
        stats = [s for s, _ in results]
        dets = [d for _, d in results]
        if len(stats) == 1:
            return stats[0], dets[0]
        return np.concatenate(stats), np.concatenate(dets)

    def scan_statuses(self, resources: List[dict]):
        """Raw (status, detail, match) matrices over all compiled programs
        — the allocation-free fast path for throughput measurement and
        report aggregation."""
        wrapped = [Resource(r) for r in resources]
        status, detail = self._device_statuses(resources)
        match = self.match_matrix(resources, wrapped)
        return status, detail, match

    # -- full responses -----------------------------------------------------

    def scan(self, resources: List[dict],
             contexts: Optional[List[dict]] = None,
             admission: Optional[tuple] = None,
             pctx_factory=None) -> List[List[EngineResponse]]:
        """Return, per resource, the engine responses of all policies with
        at least one applicable rule (host-identical).

        Webhook scans pass ``contexts`` (the admission JSON context per
        resource), ``admission`` (admission_info, exclude_group_roles,
        namespace_labels, operation) for match semantics, and
        ``pctx_factory(doc)`` so host materialization sees the same
        PolicyContext the engine loop would build."""
        n = len(resources)
        if n == 0:
            return []
        self._pctx_factory = pctx_factory
        # admission scans evaluate every policy; the background gate
        # (engine.py:174 apply_background_checks) only applies to scans
        background_mode = admission is None and pctx_factory is None
        wrapped = [Resource(r) for r in resources]
        status, detail = self._device_statuses(resources, contexts)
        match = self.match_matrix(resources, wrapped, admission)
        now = time.time()

        # which host policies could match each resource at all (group
        # screen over their simple rules; non-simple rules force a run);
        # admission scans always run host policies (operation-sensitive)
        host_maybe = self._host_policy_maybe(resources, wrapped) \
            if background_mode else \
            {p: None for p in self._host_policy_idx}

        out: List[List[EngineResponse]] = []
        for i, res_doc in enumerate(resources):
            responses: Dict[int, EngineResponse] = {}
            for j, prog in self.device_programs:
                if not match[i, j]:
                    continue
                policy = self.policies[prog.policy_index]
                if background_mode and not policy.background:
                    # background-disabled policies contribute an empty
                    # response (engine.py:174 apply_background_checks)
                    if prog.policy_index not in responses:
                        responses[prog.policy_index] = \
                            self._new_response(prog.policy_index, res_doc, now)
                    continue
                resp = responses.get(prog.policy_index)
                if resp is None:
                    resp = self._new_response(prog.policy_index, res_doc, now)
                    responses[prog.policy_index] = resp
                st = int(status[i, j])
                if st == STATUS_PASS:
                    rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                      prog.pass_messages[int(detail[i, j])],
                                      RuleStatus.PASS)
                    if prog.pss is not None:
                        rr.pod_security_checks = {
                            'level': prog.pss[0], 'version': prog.pss[1],
                            'checks': []}
                elif st == STATUS_SKIP_PRECOND:
                    rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                      PRECONDITIONS_SKIP_MESSAGE,
                                      RuleStatus.SKIP)
                elif st == STATUS_VAR_ERR:
                    rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                      prog.error_messages[int(detail[i, j])],
                                      RuleStatus.ERROR)
                elif st == STATUS_SKIP and prog.skip_message is not None:
                    # foreach 'rule skipped' is a static message
                    rr = RuleResponse(prog.rule_name, RuleType.VALIDATION,
                                      prog.skip_message, RuleStatus.SKIP)
                else:
                    # FAIL / anchor-SKIP / HOST: re-run this rule on the
                    # host for the exact status + message
                    rr = self._materialize(prog, res_doc)
                    if rr is None:
                        continue
                rr.timestamp = int(now)
                resp.policy_response.rules.append(rr)
                if rr.status in (RuleStatus.PASS, RuleStatus.FAIL):
                    resp.policy_response.rules_applied_count += 1
                elif rr.status == RuleStatus.ERROR:
                    resp.policy_response.rules_error_count += 1
            for p_idx in self._host_policy_idx:
                if host_maybe[p_idx] is None or host_maybe[p_idx][i]:
                    responses[p_idx] = self._host_run(p_idx, res_doc)
                else:
                    responses[p_idx] = self._new_response(p_idx, res_doc, now)
            out.append([responses[k] for k in sorted(responses)])
        return out

    def _host_policy_maybe(self, resources, wrapped):
        """Per host policy: bool[R] 'any rule may match', or None when the
        policy has non-simple rules (always run)."""
        from ..autogen.autogen import compute_rules
        maybe: Dict[int, Optional[np.ndarray]] = {}
        group_of = [_group_key(doc) for doc in resources]
        for p_idx in self._host_policy_idx:
            policy = self.policies[p_idx]
            rules = compute_rules(policy)
            if not all(_rule_match_is_simple(r) for r in rules):
                maybe[p_idx] = None
                continue
            cache: Dict[Tuple, bool] = {}
            flags = np.zeros(len(resources), bool)
            robj = [Rule(r) for r in rules]
            for i, key in enumerate(group_of):
                hit = cache.get(key)
                if hit is None:
                    res = wrapped[i]
                    hit = self._policy_gate(policy, res) and any(
                        matches_resource_description(
                            res, r, None, [], {}, '') is None
                        for r in robj)
                    cache[key] = hit
                flags[i] = hit
            maybe[p_idx] = flags
        return maybe

    def _pctx(self, policy: Policy, resource: dict) -> PolicyContext:
        factory = getattr(self, '_pctx_factory', None)
        if factory is not None:
            pctx = factory(resource)
            pctx = pctx.copy()
            pctx.policy = policy
            return pctx
        return PolicyContext(policy, new_resource=resource)

    def _materialize(self, prog: RuleProgram,
                     resource: dict) -> Optional[RuleResponse]:
        """Produce the exact host-engine rule response for one rule."""
        from ..engine.engine import Validator
        pctx = self._pctx(self.policies[prog.policy_index], resource)
        rule = Rule(prog.rule_raw or {})
        return Validator(self.engine, pctx, rule).validate()

    def _new_response(self, policy_index: int, resource: dict,
                      now: float) -> EngineResponse:
        policy = self.policies[policy_index]
        resp = EngineResponse(policy, patched_resource=resource)
        pr = resp.policy_response
        pr.policy_name = policy.name
        pr.policy_namespace = policy.namespace
        r = Resource(resource)
        pr.resource_name = r.name
        pr.resource_namespace = r.namespace
        pr.resource_kind = r.kind
        pr.resource_api_version = r.api_version
        pr.validation_failure_action = policy.validation_failure_action
        pr.validation_failure_action_overrides = \
            policy.validation_failure_action_overrides
        pr.timestamp = int(now)
        return resp

    def _host_run(self, policy_index: int, resource: dict) -> EngineResponse:
        policy = self.policies[policy_index]
        factory = getattr(self, '_pctx_factory', None)
        if factory is not None:
            pctx = self._pctx(policy, resource)
            return self.engine.validate(pctx)
        return self.engine.apply_background_checks(
            PolicyContext(policy, new_resource=resource))
