#!/usr/bin/env python
"""Cross-host fleet federation report.

Merges per-process metric snapshots into one fleet view — the offline
twin of a live process's ``GET /debug/fleet``.  Two modes:

  scripts/fleet_report.py host1.jsonl host2.jsonl ...
      merge per-host JSONL snapshot files (written by
      ``kyverno_tpu.observability.fleet.write_snapshot`` — one line
      per snapshot; ``bench.py --multichip`` leaves these behind) with
      the exact merge the live endpoint uses, so the CLI and a running
      process can never disagree on the math.

  scripts/fleet_report.py --url http://127.0.0.1:6060
      fetch the live fleet report from a --profile process.

``--json`` prints the machine-readable document instead of the table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def fetch_report(url: str) -> dict:
    from urllib.request import urlopen
    with urlopen(url.rstrip('/') + '/debug/fleet', timeout=10) as resp:
        return json.loads(resp.read().decode('utf-8'))


def merge_files(paths) -> dict:
    from kyverno_tpu.observability.fleet import (FleetRegistry,
                                                 read_snapshot_files)
    docs = read_snapshot_files(paths)
    if not docs:
        raise SystemExit('no snapshots found in: ' + ', '.join(paths))
    merged = FleetRegistry.merge(docs)
    return {
        'enabled': True,
        'processes': merged['identities'],
        'merged': merged,
        'skew': None,
    }


def print_table(report: dict) -> None:
    if not report.get('enabled', True):
        print('fleet observatory not configured (KTPU_FLEET=0 or no '
              '--profile registry)')
        return
    processes = report.get('processes') or []
    print(f'fleet: {len(processes)} process(es)')
    for ident in processes:
        print(f'  {ident.get("host", "?")} pid={ident.get("pid", "?")} '
              f'process_index={ident.get("process_index", "?")}')
    skew = report.get('skew')
    if skew:
        print(f'skew: {skew.get("mesh")} {float(skew.get("skew", 1)):.2f}x '
              f'slow_shard={skew.get("slow_shard")} '
              f'sustained={skew.get("sustained")}')
        if skew.get('note'):
            print(f'  {skew["note"]}')
    merged = report.get('merged') or {}
    print()
    print(f'{"merged counter":<52} {"total":>14}')
    for name, entries in (merged.get('counters') or {}).items():
        total = sum(v for _k, v in entries)
        print(f'{name:<52} {total:>14g}')
    print(f'{"merged gauge":<52} {"value":>14}')
    for name, entries in (merged.get('gauges') or {}).items():
        total = sum(v for _k, v in entries)
        print(f'{name:<52} {total:>14g}')
    hists = merged.get('hists') or {}
    if hists:
        print(f'{"merged histogram":<52} {"count":>8} {"sum":>12}')
        for name, h in hists.items():
            count = sum(e[1] for e in h.get('series') or [])
            total = sum(e[2] for e in h.get('series') or [])
            flag = '  [bucket_conflict]' if h.get('bucket_conflict') \
                else ''
            print(f'{name:<52} {count:>8d} {total:>12.6g}{flag}')


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog='fleet_report',
        description='cross-host fleet metric federation report')
    parser.add_argument('paths', nargs='*',
                        help='per-host JSONL snapshot files to merge '
                             'offline')
    parser.add_argument('--url', default='',
                        help='fetch /debug/fleet from a live --profile '
                             'process instead of merging files')
    parser.add_argument('--json', action='store_true', dest='as_json',
                        help='print the JSON document')
    args = parser.parse_args(argv)
    if args.url:
        report = fetch_report(args.url)
    elif args.paths:
        report = merge_files(args.paths)
    else:
        parser.print_usage(sys.stderr)
        return 2
    if args.as_json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        print_table(report)
    return 0


if __name__ == '__main__':
    sys.exit(main())
