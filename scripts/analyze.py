#!/usr/bin/env python
"""ktpu-lint driver: run every static-analysis pass over the tree.

    python scripts/analyze.py                  # table of findings
    python scripts/analyze.py --json           # machine-readable
    python scripts/analyze.py --strict         # nonzero on any
                                               # non-baseline finding
                                               # or stale baseline entry
    python scripts/analyze.py --write-baseline # regenerate the
                                               # grandfather file
    python scripts/analyze.py --knob-table     # README KTPU_* table
    python scripts/analyze.py --list-rules     # rule id reference

Default file set: ``kyverno_tpu/``, ``scripts/``, and ``bench.py``.
The committed baseline lives at ``.ktpu-baseline.json``; every entry
must carry a ``reason`` (``--strict`` refuses unjustified entries).
Per-line suppressions: ``# ktpu: noqa[KTPU101] -- reason``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kyverno_tpu.analysis import (Analyzer, RULES, load_baseline,  # noqa: E402
                                  write_baseline)
from kyverno_tpu.analysis.core import (DEFAULT_BASELINE,  # noqa: E402
                                       DEFAULT_SOURCE_PATHS)
from kyverno_tpu.analysis.knobs import render_knob_table  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('paths', nargs='*', default=None,
                    help='files/dirs to analyze (default: '
                         'kyverno_tpu scripts bench.py)')
    ap.add_argument('--json', action='store_true', dest='as_json')
    ap.add_argument('--strict', action='store_true',
                    help='exit nonzero on non-baseline findings, '
                         'stale baseline entries, or unjustified '
                         'baseline entries')
    ap.add_argument('--baseline', default=None,
                    help=f'baseline path (default: {DEFAULT_BASELINE})')
    ap.add_argument('--no-baseline', action='store_true',
                    help='ignore the committed baseline')
    ap.add_argument('--write-baseline', action='store_true',
                    help='grandfather every current finding into the '
                         'baseline file (then justify each entry)')
    ap.add_argument('--rules', default=None,
                    help='comma-separated rule ids to run')
    ap.add_argument('--knob-table', action='store_true',
                    help='print the generated KTPU_* README table')
    ap.add_argument('--span-table', action='store_true',
                    help='print the generated README span table')
    ap.add_argument('--debug-table', action='store_true',
                    help='print the generated README debug-endpoint '
                         'table (profiling-server route registry)')
    ap.add_argument('--list-rules', action='store_true')
    ap.add_argument('--graph-dump', default=None, metavar='FN',
                    help='debug: print the resolved callees and taint '
                         'facts for one function (bare name, '
                         'Class.method, or module:qualname); '
                         'honors --json')
    args = ap.parse_args(argv)

    if args.knob_table:
        print(render_knob_table())
        return 0
    if args.span_table:
        from kyverno_tpu.analysis.catalog_pass import render_span_table
        print(render_span_table())
        return 0
    if args.debug_table:
        from kyverno_tpu.observability.profiling import render_debug_table
        print(render_debug_table())
        return 0
    if args.list_rules:
        for rid in sorted(RULES):
            print(f'{rid}  {RULES[rid].summary}')
        return 0

    paths = args.paths or [p for p in DEFAULT_SOURCE_PATHS
                           if os.path.exists(os.path.join(REPO_ROOT, p))]
    baseline = None if args.no_baseline else \
        (args.baseline or os.path.join(REPO_ROOT, DEFAULT_BASELINE))
    rules = [r.strip() for r in args.rules.split(',')] \
        if args.rules else None
    analyzer = Analyzer(paths, REPO_ROOT, baseline_path=baseline,
                        rules=rules)

    if args.graph_dump:
        from kyverno_tpu.analysis.jitgraph import jit_graph
        graph = jit_graph(analyzer.ctx)
        matches = graph.function_by_name(args.graph_dump)
        if not matches:
            print(f'no function matches {args.graph_dump!r}',
                  file=sys.stderr)
            return 2
        dumps = [graph.graph_dump(mi, fn) for mi, fn in matches]
        if args.as_json:
            print(json.dumps(dumps, indent=2))
        else:
            for d in dumps:
                print(f'{d["qualname"]}  ({d["file"]}:{d["line"]})')
                print(f'  jit-reachable: {d["jit_reachable"]}')
                if d.get('class'):
                    print(f'  class: {d["class"]}')
                print('  callees:')
                if not d['callees']:
                    print('    (none resolved)')
                for c in d['callees']:
                    reach = ' [jit-reachable]' if c['jit_reachable'] \
                        else ''
                    print(f'    {c["qualname"]}  ({c["file"]}:'
                          f'{c["line"]}, called at line '
                          f'{c["call_line"]}){reach}')
                taint = d.get('taint') or {}
                if taint.get('params'):
                    print(f'  tainted params (depth '
                          f'{taint.get("depth")}): '
                          f'{", ".join(taint["params"])}')
                    if taint.get('chain'):
                        print(f'  taint chain: '
                              f'{" -> ".join(taint["chain"])}')
                    print(f'  tainted locals: '
                          f'{", ".join(taint.get("names", [])) or "-"}')
                else:
                    print('  tainted params: (none)')
        return 0

    report = analyzer.run()

    if args.write_baseline:
        target = baseline or os.path.join(REPO_ROOT, DEFAULT_BASELINE)
        # regenerate from every kept finding — new AND already
        # grandfathered — so a rewrite never drops still-matching
        # entries, and carry existing justifications over by key
        prior = {(e.get('rule'), e.get('path'), e.get('match')):
                 str(e.get('reason', ''))
                 for e in load_baseline(target)}
        everything = report.active + report.baselined
        write_baseline(target, everything)
        with open(target, encoding='utf-8') as fh:
            doc = json.load(fh)
        for e in doc['entries']:
            r = prior.get((e['rule'], e['path'], e['match']), '')
            if r and not r.startswith('TODO'):
                e['reason'] = r
        with open(target, 'w', encoding='utf-8') as fh:
            json.dump(doc, fh, indent=2)
            fh.write('\n')
        print(f'wrote {len(doc["entries"])} entries to {target} — '
              f'justify each "reason" before committing')
        return 0

    if args.as_json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.active:
            print(f.render())
        for e in report.stale_baseline:
            print(f'stale baseline entry: {e.get("rule")} '
                  f'{e.get("path")} ({e.get("match")!r}) no longer '
                  f'matches — remove it')
        for e in report.errors:
            print(e, file=sys.stderr)
        n_files = len(analyzer.files)
        print(f'{len(report.active)} finding(s), '
              f'{len(report.baselined)} baselined, '
              f'{len(report.suppressed)} suppressed, '
              f'{len(report.stale_baseline)} stale baseline '
              f'entr(y/ies) over {n_files} files / '
              f'{len(RULES)} rules')

    if report.active or report.errors:
        return 1
    if args.strict and report.stale_baseline:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
