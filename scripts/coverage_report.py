#!/usr/bin/env python
"""Per-rule device-placement report for a policy set.

Answers "which rules actually run on device, and why not the rest?"
without scraping metrics.  Two modes:

  scripts/coverage_report.py policy.yaml dir-of-policies/ ...
      compile the packs locally and print each rule's placement
      (device | host) with the attributed fallback reason — the same
      ``coverage.compile_placements`` the live scanner records, so this
      output and a running process's ``GET /debug/coverage`` can never
      disagree on placement.

  scripts/coverage_report.py --url http://127.0.0.1:6060
      fetch the live ledger from a --profile process (placements plus
      runtime device/host row counts and the fallback counters).

``--json`` prints the machine-readable document instead of the table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def load_policies(paths: List[str]):
    import yaml
    from kyverno_tpu.api.policy import Policy
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            files.extend(
                os.path.join(path, f) for f in sorted(os.listdir(path))
                if f.endswith(('.yaml', '.yml')))
        else:
            files.append(path)
    policies = []
    for f in files:
        with open(f, encoding='utf-8') as fh:
            for doc in yaml.safe_load_all(fh):
                if doc and doc.get('kind') in ('ClusterPolicy', 'Policy'):
                    policies.append(Policy(doc))
    return policies


def compile_report(policies) -> dict:
    """Compile-time half of the /debug/coverage document: validate/pss
    placements from the policy compiler plus mutate/generate placements
    from the bulk-apply fast-path qualifier."""
    from kyverno_tpu.compiler.apply import mutate_placements
    from kyverno_tpu.compiler.compile import compile_policies
    from kyverno_tpu.observability import coverage
    cps = compile_policies(policies)
    placements = coverage.compile_placements(policies, cps)
    placements += mutate_placements(policies)
    rules = [{
        'policy': p.policy, 'rule': p.rule, 'path': p.path,
        'placement': p.placement, 'reason': p.reason, 'detail': p.detail,
    } for p in placements]
    totals = {'device': 0, 'host': 0}
    for r in rules:
        totals[r['placement']] = totals.get(r['placement'], 0) + 1
    return {'rules': rules, 'totals': totals,
            'n_policies': len(policies)}


def fetch_report(url: str) -> dict:
    from urllib.request import urlopen
    with urlopen(url.rstrip('/') + '/debug/coverage', timeout=10) as resp:
        return json.loads(resp.read().decode('utf-8'))


def print_table(report: dict) -> None:
    rules = report.get('rules', [])
    if not rules:
        print('no rules (empty policy set or ledger not configured)')
        return
    widths = (
        max((len(r['policy']) for r in rules), default=6),
        max((len(r['rule']) for r in rules), default=4),
    )
    header = (f'{"POLICY":<{widths[0]}}  {"RULE":<{widths[1]}}  '
              f'{"PATH":<8}  {"PLACEMENT":<9}  REASON')
    print(header)
    print('-' * len(header))
    for r in rules:
        reason = r.get('reason') or ''
        eff = r.get('effective')
        placement = r['placement'] if not eff or eff == r['placement'] \
            else f"{r['placement']}→{eff}"
        line = (f'{r["policy"]:<{widths[0]}}  {r["rule"]:<{widths[1]}}  '
                f'{r["path"]:<8}  {placement:<9}  {reason}')
        if r.get('host_rows') or r.get('device_rows'):
            line += (f'  [device_rows={r.get("device_rows", 0)} '
                     f'host_rows={r.get("host_rows", 0)}]')
        print(line)
    totals = report.get('totals') or {}
    if totals:
        print('-' * len(header))
        print('totals: ' + ', '.join(f'{k}={v}'
                                     for k, v in sorted(totals.items())
                                     if v is not None))
    fallbacks = report.get('fallbacks') or {}
    for path in sorted(fallbacks):
        counts = ', '.join(f'{reason}={rows}'
                           for reason, rows in
                           sorted(fallbacks[path].items()))
        print(f'fallbacks[{path}]: {counts}')


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog='coverage_report',
        description='per-rule device-placement report')
    parser.add_argument('paths', nargs='*',
                        help='policy YAML files or directories')
    parser.add_argument('--url', default='',
                        help='fetch /debug/coverage from a live '
                             '--profile process instead of compiling')
    parser.add_argument('--json', action='store_true', dest='as_json',
                        help='print the JSON document')
    args = parser.parse_args(argv)
    if args.url:
        report = fetch_report(args.url)
    elif args.paths:
        policies = load_policies(args.paths)
        if not policies:
            print('no policies found', file=sys.stderr)
            return 1
        report = compile_report(policies)
    else:
        parser.print_usage(sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print_table(report)
    return 0


if __name__ == '__main__':
    sys.exit(main())
