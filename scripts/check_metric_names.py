#!/usr/bin/env python
"""Static check: every metric name passed to MetricsRegistry write
methods (``inc`` / ``observe`` / ``set_gauge`` / ``clear_gauge`` /
``register_histogram``) must be cataloged in
``kyverno_tpu/observability/catalog.py`` with a type and help text.

Metric names drift silently: a typo'd name forks a series and the
dashboards keep reading the dead one.  This walks the tree's ASTs,
resolves each call site's name argument (string literal, or an
UPPER_CASE module-level constant defined anywhere in the tree), and
fails on any name missing from the catalog — wired into tier-1 via
``tests/test_metric_catalog.py``.

Exit status: 0 clean, 1 violations (listed on stderr).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Tuple

WRITE_METHODS = {'inc', 'observe', 'set_gauge', 'clear_gauge',
                 'register_histogram'}

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, 'kyverno_tpu')
CATALOG_PATH = os.path.join(PACKAGE, 'observability', 'catalog.py')


#: catalog entries with no write site in the tree that are legitimately
#: alive — the ONLY names the dead-metric pass may skip, each with the
#: reason it is allowed to exist without an emitter
DEAD_METRIC_ALLOWLIST = {
    'kyverno_client_queries_total':
        'reserved for a real cluster client transport (dclient '
        'interface exists; the in-memory fake does not emit queries)',
}


def _iter_sources() -> List[str]:
    out = []
    # scripts/ is walked too: tooling must not emit uncataloged series
    for root in (PACKAGE, os.path.join(REPO_ROOT, 'scripts')):
        for base, _dirs, files in os.walk(root):
            for name in files:
                if name.endswith('.py'):
                    out.append(os.path.join(base, name))
    out.append(os.path.join(REPO_ROOT, 'bench.py'))
    return sorted(p for p in out if os.path.exists(p))


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    """UPPER_CASE module-level string assignments (metric name consts)."""
    consts: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id.isupper():
                    consts[target.id] = node.value.value
    return consts


def collect_call_sites() -> Tuple[List[Tuple[str, int, str]],
                                  List[Tuple[str, int, str]]]:
    """Returns (resolved [(path, line, metric_name)], unresolved
    [(path, line, description)]) across the tree."""
    sources = _iter_sources()
    trees: Dict[str, ast.Module] = {}
    all_consts: Dict[str, str] = {}
    for path in sources:
        with open(path, encoding='utf-8') as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError as e:
                print(f'{path}: syntax error: {e}', file=sys.stderr)
                continue
        trees[path] = tree
        all_consts.update(_module_constants(tree))

    resolved: List[Tuple[str, int, str]] = []
    unresolved: List[Tuple[str, int, str]] = []
    for path, tree in trees.items():
        local_consts = _module_constants(tree)
        rel = os.path.relpath(path, REPO_ROOT)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in WRITE_METHODS and node.args):
                continue
            arg = node.args[0]
            name: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
            elif isinstance(arg, ast.Name):
                name = local_consts.get(arg.id, all_consts.get(arg.id))
            elif isinstance(arg, ast.Attribute):
                # module.CONST spelling: resolve by attribute name
                name = all_consts.get(arg.attr)
            if name is None:
                unresolved.append((rel, node.lineno,
                                   ast.dump(arg)[:80]))
            else:
                resolved.append((rel, node.lineno, name))
    return resolved, unresolved


def load_catalog() -> Dict[str, Tuple[str, str]]:
    sys.path.insert(0, REPO_ROOT)
    from kyverno_tpu.observability.catalog import METRICS
    return {name: (m.type, m.help) for name, m in METRICS.items()}


def main() -> int:
    catalog = load_catalog()
    resolved, unresolved = collect_call_sites()
    errors: List[str] = []
    for name, (mtype, mhelp) in catalog.items():
        if mtype not in ('counter', 'gauge', 'histogram'):
            errors.append(f'catalog: {name} has invalid type {mtype!r}')
        if not mhelp.strip():
            errors.append(f'catalog: {name} has empty help text')
    used = {name for _r, _l, name in resolved}
    for rel, line, name in resolved:
        if name not in catalog:
            errors.append(
                f'{rel}:{line}: metric {name!r} not in '
                f'observability/catalog.py')
    for rel, line, desc in unresolved:
        errors.append(
            f'{rel}:{line}: metric name is not a literal or module '
            f'constant ({desc}) — uncheckable, use a constant')
    # dead-metric pass: a cataloged name with no write site anywhere in
    # the tree is fiction — dashboards read a series that never exists
    for name in catalog:
        if name not in used and name not in DEAD_METRIC_ALLOWLIST:
            errors.append(
                f'catalog: {name} has no write site in the tree — '
                f'remove the entry, add the emitter, or allowlist it '
                f'with a reason (DEAD_METRIC_ALLOWLIST)')
    if not resolved:
        errors.append('no metric call sites found — checker is broken')
    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        return 1
    print(f'ok: {len(resolved)} call sites over {len(used)} metrics, '
          f'{len(catalog)} cataloged')
    return 0


if __name__ == '__main__':
    sys.exit(main())
