#!/usr/bin/env python
"""Static check: every metric name passed to MetricsRegistry write
methods (``inc`` / ``observe`` / ``set_gauge`` / ``clear_gauge`` /
``register_histogram``) must be cataloged in
``kyverno_tpu/observability/catalog.py`` with a type and help text.

This is now a thin shim over the ktpu-lint framework's catalog pass
(``kyverno_tpu/analysis/catalog_pass.py``, rules KTPU501/502/503 in
``scripts/analyze.py``) — kept so existing invocations, the module API
used by ``tests/test_metric_catalog.py``, and the dead-metric
allowlist semantics keep working unchanged.

Exit status: 0 clean, 1 violations (listed on stderr).
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kyverno_tpu.analysis.catalog_pass import (  # noqa: E402,F401
    CATALOG_PATH, DEAD_METRIC_ALLOWLIST, PACKAGE, WRITE_METHODS,
    check_main, collect_call_sites, load_catalog)


def main() -> int:
    return check_main()


if __name__ == '__main__':
    sys.exit(main())
