#!/usr/bin/env python
"""Render / validate a pipeline Chrome-trace file (the observatory's
offline half).

    python scripts/timeline_report.py trace.json           # blame table
                                                           # + verdict
    python scripts/timeline_report.py trace.json --json    # machine-
                                                           # readable
    python scripts/timeline_report.py trace.json --check   # schema
                                                           # validation
                                                           # only

The trace comes from ``GET /debug/timeline?format=chrome`` on a live
process, or from the file a northstar ``bench.py`` run drops (path in
its ``critical_path.trace_file`` field); Perfetto
(https://ui.perfetto.dev) loads the same file directly.  ``--check``
validates against the trace-event schema subset we emit (complete 'X'
events with numeric non-negative ts/dur, matched 'B'/'E' pairs with
per-(pid,tid) monotonic timestamps) and exits nonzero on any violation
— the bench harness runs it over every trace it writes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kyverno_tpu.observability import timeline  # noqa: E402


def check(trace) -> int:
    errors = timeline.validate_chrome_trace(trace)
    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        print(f'{len(errors)} schema violation(s)', file=sys.stderr)
        return 1
    events = trace.get('traceEvents', []) if isinstance(trace, dict) \
        else trace
    print(f'ok: {len(events)} trace events')
    return 0


def report(trace, as_json: bool) -> int:
    summary = timeline.blame_from_chrome(trace)
    if as_json:
        print(json.dumps(summary, indent=2))
        return 0
    totals = summary['blame_s']
    if not totals:
        print('no exec events in trace')
        return 1
    print(f'{len(summary["scans"])} scan(s), '
          f'{summary["wall_s"]:.3f}s wall attributed\n')
    print(f'{"stage":<14}{"blame_s":>10}{"frac":>8}')
    for stage, s in sorted(totals.items(), key=lambda kv: -kv[1]):
        print(f'{stage:<14}{s:>10.4f}'
              f'{summary["blame_frac"][stage]:>8.2%}')
    print(f'\nbound_by: {summary["bound_by"]}')
    if summary['suggest']:
        knobs = ', '.join(f'{k} {v}'
                          for k, v in summary['suggest'].items())
        print(f'suggest:  {knobs}')
    if summary['note']:
        print(f'note:     {summary["note"]}')
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('trace', help='Chrome trace-event JSON file')
    ap.add_argument('--check', action='store_true',
                    help='validate the trace-event schema and exit')
    ap.add_argument('--json', action='store_true',
                    help='emit the blame summary as JSON')
    args = ap.parse_args(argv)
    try:
        with open(args.trace) as fh:
            trace = json.load(fh)
    except (OSError, ValueError) as e:
        print(f'cannot read trace {args.trace!r}: {e}', file=sys.stderr)
        return 2
    if args.check:
        return check(trace)
    return report(trace, args.json)


if __name__ == '__main__':
    sys.exit(main())
