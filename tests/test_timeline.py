"""Pipeline critical-path observatory (ISSUE 16).

Pins the observatory's contracts:

* with the recorder off (unconfigured or ``KTPU_TIMELINE=0``) the scan
  path is bit-identical to an armed run — zero-cost off;
* a multi-chunk scan leaves a fully-closed event timeline whose blame
  seconds sum to the scan wall (±5%), a registered ``bound_by``
  verdict, and the ``kyverno_tpu_pipeline_blame_seconds_total``
  counter;
* early generator close drains clean: no orphan open intervals, encode
  buffers return to the arena, the inflight gauge resets, and the next
  scan is unaffected;
* an injected stage fault surfaces as a ``retry`` event while rows
  stay complete;
* the Chrome-trace export validates against the trace-event schema
  subset (planted violations are caught) and
  ``scripts/timeline_report.py --check`` consumes the dumped file;
* forked encode workers (``KTPU_ENCODE_PROCS``) ship their stage
  timing home — capture, histogram and timeline all see the encode leg
  (the satellite-1 attribution fix).
"""

import importlib.util
import json
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402
from kyverno_tpu import faults  # noqa: E402
from kyverno_tpu.api.policy import load_policies_from_yaml  # noqa: E402
from kyverno_tpu.compiler.scan import BatchScanner  # noqa: E402
from kyverno_tpu.observability import device as devtel  # noqa: E402
from kyverno_tpu.observability import timeline as tlmod  # noqa: E402
from kyverno_tpu.observability.catalog import PIPELINE_STAGES  # noqa: E402
from kyverno_tpu.observability.metrics import MetricsRegistry  # noqa: E402
from kyverno_tpu.reports.types import build_fused_report  # noqa: E402

CAP = 16  # tiny chunk capacity so a handful of pods spans many chunks
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pods(n, seed=5):
    rng = random.Random(seed)
    return [bench.make_pod(rng, i) for i in range(n)]


@pytest.fixture(scope='module')
def policies():
    return load_policies_from_yaml(bench.PACK)


@pytest.fixture()
def scanner(policies):
    s = BatchScanner(policies)
    s.CHUNK = CAP
    return s


@pytest.fixture()
def recorder():
    rec = tlmod.configure(max_events=4096)
    assert rec is not None
    yield rec
    tlmod.disable()


def reports_of(scanner, docs, now=1234.0):
    return [build_fused_report(doc, *row)
            for doc, row in zip(docs, scanner.scan_report_results(
                docs, now=now))]


class TestOffIsFree:
    def test_disabled_timeline_is_bit_identical(self, scanner,
                                                monkeypatch):
        """Reports from an armed run match an unconfigured run match a
        ``KTPU_TIMELINE=0`` run byte-for-byte — the off branch really
        is the pre-observatory scan path."""
        docs = pods(2 * CAP + 3)
        tlmod.disable()
        baseline = reports_of(scanner, docs)
        rec = tlmod.configure(max_events=1024)
        try:
            armed = reports_of(scanner, docs)
            assert rec.n_scans >= 1  # the recorder did observe the scan
        finally:
            tlmod.disable()
        monkeypatch.setenv('KTPU_TIMELINE', '0')
        assert tlmod.configure() is None  # the env gate wins
        assert tlmod.recorder() is None
        gated = reports_of(scanner, docs)
        assert armed == baseline
        assert gated == baseline


class TestBlameAccounting:
    def test_multichunk_blame_sums_to_wall(self, scanner, recorder):
        registry = MetricsRegistry()
        devtel.configure(registry)
        try:
            docs = pods(3 * CAP + 1)
            rows = list(scanner.scan_report_results(docs))
        finally:
            devtel.disable()
        assert len(rows) == len(docs)
        assert recorder.n_scans == 1
        tl = recorder.scans()[-1]
        assert tl.open_count() == 0, 'orphan open exec intervals'
        summary = tl.summary
        assert summary is recorder.last_summary
        assert summary['bound_by'] in PIPELINE_STAGES
        assert set(summary['blame_s']) <= set(tlmod.STAGE_ORDER)
        total = sum(summary['blame_s'].values())
        # the walk bottoms out at the scan origin: blame ≈ wall
        assert total == pytest.approx(summary['wall_s'], rel=0.05)
        # executing + waiting partition each stage's blame
        for s, v in summary['blame_s'].items():
            assert summary['executing_s'][s] + summary['waiting_s'][s] \
                == pytest.approx(v, abs=1e-6)
        # exec events carry worker-thread identity across the legs
        threads = {e.thread for e in tl.events if e.kind == 'exec'}
        assert any(t.startswith('ktpu-pipe-') for t in threads)
        stages = {e.stage for e in tl.events if e.kind == 'exec'}
        for s in ('encode', 'device_eval', 'd2h'):
            assert s in stages, f'no exec interval for {s}'
        # the blame counter saw the same seconds
        assert registry.counter_total(tlmod.PIPELINE_BLAME) == \
            pytest.approx(total, rel=1e-6)


class TestEarlyClose:
    def test_early_generator_close_drains_clean(self, scanner, recorder):
        registry = MetricsRegistry()
        devtel.configure(registry)
        released = []
        inner_release = scanner._arena.release

        def counting_release(batch):
            released.append(1)
            return inner_release(batch)
        scanner._arena.release = counting_release
        try:
            docs = pods(4 * CAP)
            gen = scanner.scan_report_results(docs)
            next(gen)
            gen.close()
            assert recorder.n_scans == 1
            tl = recorder.scans()[-1]
            assert tl.open_count() == 0, \
                'early close left open exec intervals'
            assert tl.summary is not None  # finalized despite the abort
            assert released, 'early close returned no buffers to arena'
            assert registry.gauge_value(
                'kyverno_tpu_scan_pipeline_inflight_chunks') == 0.0
            # the scanner is fully reusable after the abort
            rows = list(scanner.scan_report_results(docs))
            assert len(rows) == len(docs)
            assert recorder.scans()[-1].open_count() == 0
        finally:
            scanner._arena.release = inner_release
            devtel.disable()


class TestRetries:
    def test_injected_fault_lands_as_retry_event(self, scanner,
                                                 recorder):
        docs = pods(3 * CAP)
        # warm first so compile/jit noise stays out of the fault scan
        for _ in scanner.scan_report_results(docs[:CAP]):
            pass
        # second device_eval dispatch of the scan below fails once; the
        # pipeline's per-chunk retry budget absorbs it
        faults.configure('site=device_eval,nth=2')
        try:
            rows = list(scanner.scan_report_results(docs))
        finally:
            faults.disable()
        assert len(rows) == len(docs), 'retry did not recover the chunk'
        tl = recorder.scans()[-1]
        retries = [e for e in tl.events if e.kind == 'retry']
        assert retries, 'injected fault produced no retry event'
        assert retries[0].stage == 'device_eval'
        assert retries[0].attempt >= 1
        assert tl.open_count() == 0
        total = sum(tl.summary['blame_s'].values())
        assert total == pytest.approx(tl.summary['wall_s'], rel=0.05)


class TestChromeTrace:
    def test_export_validates_and_roundtrips(self, scanner, recorder,
                                             tmp_path):
        docs = pods(2 * CAP + 1)
        rows = list(scanner.scan_report_results(docs))
        assert len(rows) == len(docs)
        trace = recorder.chrome_trace()
        assert tlmod.validate_chrome_trace(trace) == []
        names = {e['name'] for e in trace['traceEvents']
                 if e.get('ph') == 'X'}
        assert 'device_eval' in names and 'encode' in names
        # the offline analyzer reconstructs blame from the trace alone
        offline = tlmod.blame_from_chrome(trace)
        assert offline['bound_by'] in PIPELINE_STAGES
        assert offline['wall_s'] > 0
        path = str(tmp_path / 'trace.json')
        assert tlmod.dump_chrome_trace(path) == path
        with open(path) as fh:
            loaded = json.load(fh)
        assert tlmod.validate_chrome_trace(loaded) == []

    def test_validator_catches_planted_violations(self):
        ok = [{'ph': 'M', 'pid': 1, 'tid': 0, 'name': 'process_name',
               'args': {'name': 's'}},
              {'ph': 'X', 'pid': 1, 'tid': 1, 'ts': 0.0, 'dur': 5.0,
               'name': 'encode'},
              {'ph': 'B', 'pid': 1, 'tid': 2, 'ts': 1.0, 'name': 'w'},
              {'ph': 'E', 'pid': 1, 'tid': 2, 'ts': 2.0, 'name': 'w'}]
        assert tlmod.validate_chrome_trace({'traceEvents': ok}) == []
        assert tlmod.validate_chrome_trace(
            [{'ph': 'X', 'pid': 1, 'tid': 1, 'ts': 0.0,
              'name': 'encode'}])  # X without dur
        assert tlmod.validate_chrome_trace(
            [{'ph': 'X', 'pid': 1, 'tid': 1, 'ts': -1.0, 'dur': 1.0,
              'name': 'x'}])  # negative ts
        assert tlmod.validate_chrome_trace(
            [{'ph': 'E', 'pid': 1, 'tid': 1, 'ts': 1.0,
              'name': 'w'}])  # E without B
        assert tlmod.validate_chrome_trace(
            [{'ph': 'B', 'pid': 1, 'tid': 1, 'ts': 1.0,
              'name': 'w'}])  # unclosed B
        backwards = [{'ph': 'B', 'pid': 1, 'tid': 1, 'ts': 5.0,
                      'name': 'a'},
                     {'ph': 'E', 'pid': 1, 'tid': 1, 'ts': 1.0,
                      'name': 'a'}]
        assert any('monotonic' in e
                   for e in tlmod.validate_chrome_trace(backwards))
        assert tlmod.validate_chrome_trace({'nope': 1})  # no traceEvents

    def test_report_script_check_mode(self, scanner, recorder,
                                      tmp_path):
        docs = pods(CAP + 1)
        list(scanner.scan_report_results(docs))
        path = str(tmp_path / 'trace.json')
        assert tlmod.dump_chrome_trace(path) == path
        spec = importlib.util.spec_from_file_location(
            'timeline_report',
            os.path.join(REPO, 'scripts', 'timeline_report.py'))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([path, '--check']) == 0
        assert mod.main([path, '--json']) == 0
        assert mod.main([path]) == 0
        bad = str(tmp_path / 'bad.json')
        with open(bad, 'w') as fh:
            json.dump({'traceEvents': [{'ph': 'X', 'ts': 0.0,
                                        'name': 'x'}]}, fh)
        assert mod.main([bad, '--check']) == 1
        assert mod.main([str(tmp_path / 'missing.json'),
                         '--check']) == 2


class TestForkedEncodeAttribution:
    def test_forked_workers_ship_stage_time_home(self, policies,
                                                 recorder, monkeypatch):
        """KTPU_ENCODE_PROCS workers encode in a forked process; their
        measured encode seconds must land in the ambient ScanCapture,
        the stage histogram and the timeline — not silently vanish
        (the regression this pins re-installed capture context on the
        process side)."""
        monkeypatch.setenv('KTPU_ENCODE_PROCS', '1')
        registry = MetricsRegistry()
        devtel.configure(registry)
        scanner = BatchScanner(policies)
        scanner.CHUNK = CAP
        scanner.ENCODE_TIMEOUT_S = 60
        try:
            docs = pods(3 * CAP)
            cap = devtel.ScanCapture()
            with devtel.install_capture(cap):
                rows = list(scanner.scan_report_results(docs))
            assert len(rows) == len(docs)
            assert not scanner._encoder_pool._broken, \
                'forked encode pool fell back to in-process'
            # capture attribution survived the fork boundary
            assert cap.stage_s('encode') > 0.0
            # the timeline shows the worker-process encode interval
            tl = recorder.scans()[-1]
            enc_threads = {e.thread for e in tl.events
                           if e.kind == 'exec' and e.stage == 'encode'}
            assert any(t.startswith('ktpu-encproc-')
                       for t in enc_threads), enc_threads
            # and the scan's critical path landed on the capture
            assert cap.critical_path is not None
            assert cap.critical_path['bound_by'] in PIPELINE_STAGES
        finally:
            scanner._encoder_pool.close()
            devtel.disable()
