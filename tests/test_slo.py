"""Serving SLO engine (ISSUE 14 tentpole b).

Multi-window burn-rate math over the sliding time-ring digests, the
degraded-transition auto-profile (exactly one, rate-limited), the
``KTPU_SLO_WINDOW_S=0`` off-state bit-identity on the admission path,
the aggregate ``GET /health`` verdict payload, and the acceptance
drill: a fault-injected slow handler crossing the burn threshold fires
exactly one auto-profile.  CPU-only, tier-1.
"""

import json

import yaml

from kyverno_tpu import faults
from kyverno_tpu.api.policy import Policy
from kyverno_tpu.config.config import Configuration
from kyverno_tpu.observability import executables, slo
from kyverno_tpu.observability.metrics import MetricsRegistry
from kyverno_tpu.observability.slo import (BURN_DEGRADED,
                                           PROFILE_MIN_INTERVAL_S,
                                           SLO_BUDGET_REMAINING,
                                           SLO_BURN_RATE, SloEngine)
from kyverno_tpu.policycache.cache import Cache
from kyverno_tpu.webhooks.handlers import ResourceHandlers
from kyverno_tpu.webhooks.server import WebhookServer

ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""


import pytest


@pytest.fixture(autouse=True)
def _clean_modules():
    yield
    slo.disable()
    executables.disable()
    faults.disable()


class FakeClock:
    def __init__(self, t=10_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_engine(window_s=120.0, p99_ms=100.0, target=0.9,
                registry=None, profile_trigger=None):
    clock = FakeClock()
    eng = SloEngine(window_s=window_s, p99_ms=p99_ms, target=target,
                    registry=registry or MetricsRegistry(), now=clock,
                    profile_trigger=profile_trigger or (lambda: None))
    return eng, clock


def make_cache(*policy_yamls):
    cache = Cache()
    policies = [Policy(d) for y in policy_yamls
                for d in yaml.safe_load_all(y)]
    cache.warm_up(policies)
    return cache


def pod(labels=None, name='test-pod'):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'labels': labels or {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


def review(resource, uid='uid-1'):
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {
            'uid': uid,
            'kind': {'group': '', 'version': 'v1',
                     'kind': resource.get('kind', '')},
            'namespace': resource['metadata'].get('namespace', ''),
            'name': resource['metadata'].get('name', ''),
            'operation': 'CREATE',
            'object': resource,
            'userInfo': {'username': 'alice', 'groups': []},
        }}).encode()


class TestBurnMath:
    def test_within_objective_burns_nothing(self):
        eng, _ = make_engine()
        for _ in range(20):
            eng.record('batch', 0.010)  # 10ms < 100ms objective
        v = eng.verdict()
        assert v['burn_rate_long'] == 0.0
        assert v['burn_rate_short'] == 0.0
        assert v['budget_remaining'] == 1.0
        assert v['degraded'] is False

    def test_all_over_objective_burns_at_inverse_budget(self):
        # target 0.9 → budget 0.1; 100% over-objective → burn 10.0
        eng, _ = make_engine()
        for _ in range(10):
            eng.record('sync', 0.500)
        v = eng.verdict()
        assert abs(v['burn_rate_long'] - 10.0) < 1e-9
        assert abs(v['burn_rate_short'] - 10.0) < 1e-9
        assert v['degraded'] is True

    def test_degraded_requires_both_windows(self):
        # old slices carry the errors; the current (short) slice is
        # clean → the long window burns but the verdict holds
        eng, clock = make_engine()
        for _ in range(10):
            eng.record('batch', 0.500)
        clock.advance(eng.slice_s * 2)
        for _ in range(40):
            eng.record('batch', 0.001)
        v = eng.verdict()
        assert v['burn_rate_short'] == 0.0
        assert v['burn_rate_long'] >= BURN_DEGRADED
        # a fresh recording recomputes the flag from both windows
        eng.record('batch', 0.001)
        assert eng.verdict()['degraded'] is False

    def test_window_expiry_forgets_old_slices(self):
        eng, clock = make_engine()
        for _ in range(10):
            eng.record('batch', 0.500)
        clock.advance(eng.window_s + eng.slice_s)
        eng.record('batch', 0.001)
        v = eng.verdict()
        assert v['burn_rate_long'] == 0.0
        assert v['budget_remaining'] == 1.0

    def test_gauges_published(self):
        reg = MetricsRegistry()
        eng, _ = make_engine(registry=reg)
        eng.record('batch', 0.500)
        assert reg.gauge_value(SLO_BURN_RATE, window='long') == 10.0
        assert reg.gauge_value(SLO_BURN_RATE, window='short') == 10.0
        assert reg.gauge_value(SLO_BUDGET_REMAINING) == -9.0

    def test_burn_gauges_reset_on_close(self):
        """Burn rate and budget are live conditions of this process:
        the publish path marks them reset-on-close, so a drained
        server scrapes as healthy (0), not as its last degraded
        sample."""
        reg = MetricsRegistry()
        eng, _ = make_engine(registry=reg)
        eng.record('batch', 0.500)
        assert reg.gauge_value(SLO_BURN_RATE, window='short') == 10.0
        reg.reset_residency_gauges()
        assert reg.gauge_value(SLO_BURN_RATE, window='short') == 0.0
        assert reg.gauge_value(SLO_BURN_RATE, window='long') == 0.0
        assert reg.gauge_value(SLO_BUDGET_REMAINING) == 0.0

    def test_snapshot_per_path_digests(self):
        eng, _ = make_engine()
        for _ in range(98):
            eng.record('batch', 0.004)
        eng.record('batch', 0.900)
        eng.record('batch', 0.900)
        eng.record('shed', 0.020)
        snap = eng.snapshot()
        assert set(snap['paths']) == {'batch', 'shed'}
        b = snap['paths']['batch']
        assert b['count'] == 100 and b['over_objective'] == 2
        # upper-bound bucket estimates: p50 in the 5ms bucket, p99
        # reaches the 1000ms bucket holding the one slow decision
        assert b['p50_ms'] == 5.0
        assert b['p99_ms'] == 1000.0


class TestAutoProfile:
    def test_exactly_one_profile_on_transition(self):
        fired = []
        eng, clock = make_engine(profile_trigger=lambda: fired.append(1))
        for _ in range(10):
            eng.record('batch', 0.500)
        assert eng.auto_profiles == 1
        # still degraded: no re-fire while the verdict holds
        for _ in range(10):
            eng.record('batch', 0.500)
        assert eng.auto_profiles == 1

    def test_rate_limit_holds_across_flaps(self):
        eng, clock = make_engine()
        eng.profile_trigger = lambda: None
        for _ in range(10):
            eng.record('batch', 0.500)
        assert eng.auto_profiles == 1
        # recover (clean slice), then degrade again inside the 60s
        # floor: the transition happens but the capture is suppressed
        clock.advance(eng.slice_s)
        eng.record('batch', 0.001)
        assert eng.verdict()['degraded'] is False
        for _ in range(10):
            eng.record('batch', 0.500)
        assert eng.verdict()['degraded'] is True
        assert eng.auto_profiles == 1
        # past the floor, a fresh transition captures again
        clock.advance(PROFILE_MIN_INTERVAL_S + eng.slice_s)
        eng.record('batch', 0.001)
        for _ in range(10):
            eng.record('batch', 0.500)
        assert eng.auto_profiles == 2


class TestModuleState:
    def test_noop_until_configured(self):
        assert not slo.enabled()
        slo.record('batch', 99.0)  # must not raise
        assert slo.verdict() is None
        assert slo.snapshot() == {}

    def test_env_window_zero_disables(self, monkeypatch):
        monkeypatch.delenv('KTPU_SLO_WINDOW_S', raising=False)
        assert slo.configure(registry=MetricsRegistry()) is None
        monkeypatch.setenv('KTPU_SLO_WINDOW_S', '0')
        assert slo.configure(registry=MetricsRegistry()) is None
        assert not slo.enabled()

    def test_env_knobs_shape_the_engine(self, monkeypatch):
        monkeypatch.setenv('KTPU_SLO_WINDOW_S', '240')
        monkeypatch.setenv('KTPU_SLO_P99_MS', '50')
        monkeypatch.setenv('KTPU_SLO_TARGET', '0.95')
        eng = slo.configure(registry=MetricsRegistry())
        assert eng.window_s == 240.0
        assert eng.objective_ms == 50.0
        assert eng.target == 0.95
        assert slo.enabled()

    def test_shed_reason_folds_to_lane(self):
        eng = slo.configure(registry=MetricsRegistry(), window_s=60.0,
                            p99_ms=100.0, target=0.9)
        slo.record('shed:queue_full', 0.001)
        assert set(eng.snapshot()['paths']) == {'shed'}


class TestAdmissionIntegration:
    def _serve(self):
        handlers = ResourceHandlers(make_cache(ENFORCE_POLICY),
                                    device=False)
        return WebhookServer(handlers, configuration=Configuration())

    def test_off_state_is_bit_identical(self):
        """KTPU_SLO_WINDOW_S=0 (and the executables ledger off): the
        admission response bytes are identical to a run with both
        enabled — telemetry never reaches the payload."""
        server = self._serve()
        body_off = server.handle('/validate/fail',
                                 review(pod(), uid='u-bit'))
        slo.configure(registry=MetricsRegistry(), window_s=60.0,
                      p99_ms=100.0, target=0.9)
        executables.configure(registry=MetricsRegistry(), ledger_n=16)
        body_on = server.handle('/validate/fail',
                                review(pod(), uid='u-bit'))
        assert body_on == body_off
        # ...and the engine really observed the decision
        snap = slo.snapshot()
        assert sum(p['count'] for p in snap['paths'].values()) == 1

    def test_handler_feeds_serving_path(self):
        eng = slo.configure(registry=MetricsRegistry(), window_s=60.0,
                            p99_ms=10_000.0, target=0.9)
        server = self._serve()
        server.handle('/validate/fail', review(pod()))
        snap = eng.snapshot()
        assert snap['paths'], snap
        assert not snap['degraded']

    def test_health_carries_verdict_payload_only(self):
        server = self._serve()
        body, code = server.health_status()
        assert 'slo' not in body  # engine off → no verdict key
        slo.configure(registry=MetricsRegistry(), window_s=60.0,
                      p99_ms=0.0001, target=0.9)
        for _ in range(5):
            server.handle('/validate/fail', review(pod()))
        body, code = server.health_status()
        assert body['slo']['degraded'] is True
        # degraded never changes the status code: readiness only
        assert code == (200 if body['ready'] else 503)

    def test_burn_crossing_fires_one_auto_profile(self):
        """ISSUE 14 acceptance: a fault-injected slow handler (device
        path raises → every request host-fallbacks past a microscopic
        objective) crosses the burn threshold and fires exactly one
        rate-limited auto-profile."""
        fired = []
        slo.configure(registry=MetricsRegistry(), window_s=600.0,
                      p99_ms=0.0001, target=0.99,
                      profile_trigger=lambda: fired.append(1))
        faults.configure('site=webhook_handler,p=1')
        handlers = ResourceHandlers(make_cache(ENFORCE_POLICY),
                                    device=True)
        server = WebhookServer(handlers, configuration=Configuration())
        inj = faults.active()
        for k in range(8):
            body = server.handle('/validate/fail',
                                 review(pod(), uid=f'u{k}'))
            assert json.loads(body)['response']  # served, not 500
        assert inj.counts().get('webhook_handler', 0) >= 1
        eng = slo.engine()
        assert eng.verdict()['degraded'] is True
        snap = eng.snapshot()
        assert 'host_fallback' in snap['paths']
        assert eng.auto_profiles == 1
        # the capture thread is fire-and-forget; join via the counter
        import time as _time
        deadline = _time.time() + 5.0
        while not fired and _time.time() < deadline:
            _time.sleep(0.01)
        assert len(fired) == 1
