"""Unique-program-space evaluation: replicated policies must produce
host-identical responses while the device graph and readback stay
O(unique rules).

Reference scale scenario: a cluster with ~1k installed policies that are
copies/variants of a small pack (the admission latency benchmark's
shape).
"""

import copy

import pytest

from kyverno_tpu.api.policy import Policy, load_policies_from_yaml
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: disallow-latest
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: Enforce
  rules:
    - name: no-latest
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "latest tag not allowed"
        pattern:
          spec:
            containers:
              - image: "!*:latest"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-run-as-non-root
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: check-containers
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "runAsNonRoot required"
        anyPattern:
          - spec:
              securityContext:
                runAsNonRoot: true
          - spec:
              containers:
                - securityContext:
                    runAsNonRoot: true
"""

PODS = [
    {'apiVersion': 'v1', 'kind': 'Pod',
     'metadata': {'name': 'good', 'namespace': 'default'},
     'spec': {'containers': [
         {'name': 'c', 'image': 'nginx:1.25',
          'securityContext': {'runAsNonRoot': True}}]}},
    {'apiVersion': 'v1', 'kind': 'Pod',
     'metadata': {'name': 'bad', 'namespace': 'default'},
     'spec': {'containers': [{'name': 'c', 'image': 'nginx:latest'}]}},
    {'apiVersion': 'v1', 'kind': 'Pod',
     'metadata': {'name': 'nonroot-missing', 'namespace': 'default'},
     'spec': {'containers': [{'name': 'c', 'image': 'redis:7'}]}},
]


def replicate(policies, n):
    out = []
    i = 0
    while len(out) < n:
        for p in policies:
            doc = copy.deepcopy(p.raw)
            doc['metadata']['name'] = f"{doc['metadata']['name']}-r{i}"
            out.append(Policy(doc))
            if len(out) >= n:
                break
        i += 1
    return out


@pytest.fixture(scope='module')
def replicated_scanner():
    policies = replicate(load_policies_from_yaml(PACK), 40)
    return policies, BatchScanner(policies)


def test_unique_space_is_small(replicated_scanner):
    _, scanner = replicated_scanner
    ev = scanner._evaluator
    assert ev.n_programs == 40
    assert ev.n_uniq == 2  # one per distinct rule tree
    assert not ev.expand_identity
    # every program column maps back to one of the unique columns
    assert set(ev.uniq_idx.tolist()) == {0, 1}


def test_replicated_scan_matches_host(replicated_scanner):
    policies, scanner = replicated_scanner
    engine = Engine()
    out = scanner.scan(PODS)
    assert len(out) == len(PODS)
    for doc, responses in zip(PODS, out):
        got = {r.policy_response.policy_name:
               {rr.name: (rr.status, rr.message)
                for rr in r.policy_response.rules}
               for r in responses if r.policy_response.rules}
        host = {}
        for policy in policies:
            hr = engine.apply_background_checks(
                PolicyContext(policy, new_resource=doc))
            if hr.policy_response.rules:
                host[policy.name] = {
                    rr.name: (rr.status, rr.message)
                    for rr in hr.policy_response.rules}
        assert got == host, doc['metadata']['name']


def test_fold_and_expand_roundtrip(replicated_scanner):
    import numpy as np
    from kyverno_tpu.ops.eval import fold_match_unique
    _, scanner = replicated_scanner
    ev = scanner._evaluator
    rng = np.random.RandomState(0)
    mm = (rng.rand(8, ev.n_programs) < 0.5).astype(np.uint8)
    folded = fold_match_unique(mm, ev)
    assert folded.shape == (8, ev.n_uniq)
    for u, cols in enumerate(ev.uniq_groups):
        assert (folded[:, u] == mm[:, cols].max(axis=1)).all()
