"""Policy cache, dynamic config, and report pipeline tests
(reference behavior: pkg/policycache/cache_test.go,
pkg/config/config.go, pkg/utils/report, report aggregate controller)."""

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.config import ConfigController, Configuration
from kyverno_tpu.dclient import FakeClient
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.policycache import (
    GENERATE, MUTATE, VALIDATE_AUDIT, VALIDATE_ENFORCE, Cache,
)
from kyverno_tpu.reports import (
    AggregateController, build_admission_report,
    engine_response_to_report_results, new_background_scan_report,
)
from kyverno_tpu.reports.results import set_responses


def _policy(name, kinds=('Pod',), action='Audit', rule_type='validate',
            namespace='', overrides=None):
    rule = {'name': 'r', 'match': {'any': [{'resources':
                                            {'kinds': list(kinds)}}]}}
    if rule_type == 'validate':
        rule['validate'] = {'pattern': {'spec': {'x': '?*'}}}
    elif rule_type == 'mutate':
        rule['mutate'] = {'patchStrategicMerge': {'metadata': {
            'labels': {'a': 'b'}}}}
    elif rule_type == 'generate':
        rule['generate'] = {'kind': 'ConfigMap', 'name': 'x',
                            'namespace': 'default', 'data': {}}
    raw = {'apiVersion': 'kyverno.io/v1',
           'kind': 'Policy' if namespace else 'ClusterPolicy',
           'metadata': {'name': name,
                        'annotations': {
                            'pod-policies.kyverno.io/autogen-controllers':
                            'none'}},
           'spec': {'rules': [rule],
                    'validationFailureAction': action}}
    if namespace:
        raw['metadata']['namespace'] = namespace
    if overrides:
        raw['spec']['validationFailureActionOverrides'] = overrides
    return Policy(raw)


class TestPolicyCache:
    def test_type_index(self):
        cache = Cache()
        cache.set('audit-pol', _policy('audit-pol', action='Audit'))
        cache.set('enforce-pol', _policy('enforce-pol', action='Enforce'))
        cache.set('mut', _policy('mut', rule_type='mutate'))
        cache.set('gen', _policy('gen', rule_type='generate'))
        # enforce policies join the audit candidate list (cache.go:47) but
        # are filtered back out unless an override makes them audit in ns
        audit = [p.name for p in cache.get_policies(VALIDATE_AUDIT, 'Pod')]
        assert set(audit) == {'audit-pol'}
        enforce = [p.name for p in cache.get_policies(VALIDATE_ENFORCE, 'Pod')]
        assert enforce == ['enforce-pol']
        assert [p.name for p in cache.get_policies(MUTATE, 'Pod')] == ['mut']
        assert [p.name for p in cache.get_policies(GENERATE, 'Pod')] == ['gen']
        assert cache.get_policies(MUTATE, 'Service') == []

    def test_namespace_override_filtering(self):
        cache = Cache()
        cache.set('p', _policy(
            'p', action='Audit',
            overrides=[{'action': 'Enforce', 'namespaces': ['prod-*']}]))
        assert [p.name for p in
                cache.get_policies(VALIDATE_ENFORCE, 'Pod', 'prod-eu')] == ['p']
        # in the override'd namespace the audit lookup drops the policy
        assert cache.get_policies(VALIDATE_AUDIT, 'Pod', 'prod-eu') == []
        # elsewhere the base Audit action applies
        assert [p.name for p in
                cache.get_policies(VALIDATE_AUDIT, 'Pod', 'dev')] == ['p']

    def test_enforce_policy_with_audit_override_in_ns(self):
        cache = Cache()
        cache.set('e', _policy(
            'e', action='Enforce',
            overrides=[{'action': 'Audit', 'namespaces': ['sandbox']}]))
        assert [p.name for p in
                cache.get_policies(VALIDATE_AUDIT, 'Pod', 'sandbox')] == ['e']
        assert cache.get_policies(VALIDATE_ENFORCE, 'Pod', 'sandbox') == []
        assert [p.name for p in
                cache.get_policies(VALIDATE_ENFORCE, 'Pod', 'prod')] == ['e']

    def test_namespaced_policy_scoping(self):
        cache = Cache()
        cache.set('team-a/p', _policy('p', namespace='team-a'))
        assert [p.name for p in
                cache.get_policies(VALIDATE_AUDIT, 'Pod', 'team-a')] == ['p']
        assert cache.get_policies(VALIDATE_AUDIT, 'Pod', 'team-b') == []
        assert cache.get_policies(VALIDATE_AUDIT, 'Pod', '') == []

    def test_unset(self):
        cache = Cache()
        cache.set('p', _policy('p'))
        cache.unset('p')
        assert cache.get_policies(VALIDATE_AUDIT, 'Pod') == []

    def test_wildcard_kind(self):
        cache = Cache()
        cache.set('w', _policy('w', kinds=['*']))
        assert [p.name for p in
                cache.get_policies(VALIDATE_AUDIT, 'Secret')] == ['w']


class TestConfiguration:
    def test_defaults(self):
        cfg = Configuration()
        assert cfg.get_default_registry() == 'docker.io'
        assert 'system:nodes' in cfg.get_exclude_group_role()
        assert not cfg.to_filter('Pod', 'default', 'x')

    def test_load_and_filter(self):
        cfg = Configuration()
        cfg.load({'data': {
            'resourceFilters':
                '[Event,*,*][*,kube-system,*][Secret,*,no-scan-*]',
            'excludeGroupRole': 'system:custom',
            'excludeUsername': 'admin,ci-bot',
            'defaultRegistry': 'registry.example.com:5000',
            'generateSuccessEvents': 'true',
        }})
        assert cfg.to_filter('Event', 'default', 'e1')
        assert cfg.to_filter('Pod', 'kube-system', 'p')
        assert cfg.to_filter('Secret', 'app', 'no-scan-1')
        assert not cfg.to_filter('Secret', 'app', 'scan-me')
        assert 'system:custom' in cfg.get_exclude_group_role()
        assert 'system:nodes' in cfg.get_exclude_group_role()
        assert cfg.get_exclude_username() == ['admin', 'ci-bot']
        assert cfg.get_default_registry() == 'registry.example.com:5000'
        assert cfg.get_generate_success_events()

    def test_hot_reload_via_controller(self):
        client = FakeClient()
        cfg = Configuration()
        ConfigController(client, cfg)
        client.create_resource('v1', 'ConfigMap', 'kyverno', {
            'apiVersion': 'v1', 'kind': 'ConfigMap',
            'metadata': {'name': 'kyverno', 'namespace': 'kyverno'},
            'data': {'resourceFilters': '[Node,*,*]'}})
        assert cfg.to_filter('Node', '', 'n1')
        client.delete_resource('v1', 'ConfigMap', 'kyverno', 'kyverno')
        assert not cfg.to_filter('Node', '', 'n1')


def _engine_response(policy, resource):
    return Engine().validate(PolicyContext(policy=policy,
                                           new_resource=resource))


def _pod(name='p', namespace='default', uid='uid-1', compliant=False):
    spec = {'containers': [{'name': 'c', 'image': 'nginx:1'}]}
    if compliant:
        spec['x'] = 'ok'
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': namespace, 'uid': uid},
            'spec': spec}


class TestReportResults:
    def test_mapping_fields(self):
        policy = _policy('check')
        resp = _engine_response(policy, _pod())
        results = engine_response_to_report_results(resp, now=1234)
        assert len(results) == 1
        r = results[0]
        assert r['source'] == 'kyverno'
        assert r['policy'] == 'check'
        assert r['rule'] == 'r'
        assert r['result'] == 'fail'
        assert r['scored'] is True
        assert r['timestamp'] == {'seconds': 1234}

    def test_unscored_fail_becomes_warn(self):
        policy = _policy('check')
        policy.raw['metadata']['annotations'][
            'policies.kyverno.io/scored'] = 'false'
        resp = _engine_response(policy, _pod())
        results = engine_response_to_report_results(resp, now=1)
        assert results[0]['result'] == 'warn'
        assert results[0]['scored'] is False

    def test_admission_report_builder(self):
        policy = _policy('check')
        pod = _pod()
        resp = _engine_response(policy, pod)
        report = build_admission_report(
            pod, {'uid': 'req-1'}, resp, now=1)
        assert report['kind'] == 'AdmissionReport'
        assert report['metadata']['name'] == 'req-1'
        assert report['spec']['summary'] == {'pass': 0, 'fail': 1,
                                             'warn': 0, 'error': 0,
                                             'skip': 0}
        assert report['metadata']['labels'][
            'audit.kyverno.io/resource.uid'] == 'uid-1'


class TestAggregation:
    def _store_scan_report(self, client, policy, pod, now):
        report = new_background_scan_report(pod)
        resp = _engine_response(policy, pod)
        set_responses(report, resp, now=now)
        client.create_resource('kyverno.io/v1alpha2', report['kind'],
                               (pod['metadata'].get('namespace', '')), report)

    def test_merge_to_policy_report(self):
        client = FakeClient()
        policy = _policy('check')
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               policy.raw)
        pod1 = _pod('p1', uid='u1')
        pod2 = _pod('p2', uid='u2', compliant=True)
        self._store_scan_report(client, policy, pod1, now=10)
        self._store_scan_report(client, policy, pod2, now=10)
        ctrl = AggregateController(client)
        reports = ctrl.reconcile()
        assert len(reports) == 1
        pr = reports[0]
        assert pr['kind'] == 'PolicyReport'
        assert pr['metadata']['name'] == 'cpol-check'
        assert pr['summary']['fail'] == 1 and pr['summary']['pass'] == 1
        uids = {r['resources'][0]['uid'] for r in pr['results']}
        assert uids == {'u1', 'u2'}

    def test_newest_result_wins_and_stale_policies_dropped(self):
        client = FakeClient()
        policy = _policy('check')
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               policy.raw)
        pod = _pod('p1', uid='u1')
        self._store_scan_report(client, policy, pod, now=10)
        # newer admission report for the same resource: compliant now
        resp = _engine_response(policy, _pod('p1', uid='u1', compliant=True))
        report = build_admission_report(pod, {'uid': 'r1'}, resp, now=20)
        client.create_resource('kyverno.io/v1alpha2', 'AdmissionReport',
                               'default', report)
        ctrl = AggregateController(client)
        reports = ctrl.reconcile()
        assert reports[0]['summary'] == {'pass': 1, 'fail': 0, 'warn': 0,
                                         'error': 0, 'skip': 0}
        # deleting the policy removes its results and the report cleans up
        client.delete_resource('kyverno.io/v1', 'ClusterPolicy', '', 'check')
        reports = ctrl.reconcile()
        assert all(not r.get('results') for r in reports)
