"""Tracing + profiling subsystem (reference: pkg/tracing/childspan.go,
pkg/webhooks/handlers/trace.go:16, pkg/profiling/pprof.go)."""

import json
import urllib.request

import pytest

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import tracing
from kyverno_tpu.observability.profiling import ProfilingServer
from kyverno_tpu.policycache.cache import Cache
from kyverno_tpu.webhooks.handlers import ResourceHandlers
from kyverno_tpu.webhooks.server import WebhookServer

POLICY = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'require-labels', 'annotations': {
        'pod-policies.kyverno.io/autogen-controllers': 'none'}},
    'spec': {'validationFailureAction': 'Enforce', 'rules': [
        {'name': 'check-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'app label required',
                      'pattern': {'metadata': {'labels': {'app': '?*'}}}}},
        {'name': 'check-team',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'team label required',
                      'pattern': {'metadata': {'labels': {'team': '?*'}}}}},
    ]}}


def review(doc):
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {'uid': 'u1', 'operation': 'CREATE',
                    'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
                    'namespace': 'default', 'name': 'p',
                    'object': doc,
                    'userInfo': {'username': 'tester'}}}).encode()


def pod():
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'namespace': 'default',
                         'labels': {'app': 'x'}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}


@pytest.fixture
def mem():
    exporter = tracing.configure()
    yield exporter
    tracing.disable()


class TestSpans:
    def test_admission_request_span_hierarchy(self, mem):
        cache = Cache()
        cache.warm_up([Policy(POLICY)])
        server = WebhookServer(ResourceHandlers(cache, device=False))
        server.handle('/validate/fail', review(pod()))

        [root] = mem.find('webhooks/validate/fail')
        assert root.parent_id == ''
        assert root.attributes['operation'] == 'CREATE'
        # the pod carries 'app' but not 'team' → enforce denies
        assert root.attributes['allowed'] is False
        rule_spans = mem.find('kyverno/engine/rule')
        assert len(rule_spans) == 2
        for span in rule_spans:
            # rule spans nest under the handler span, same trace
            assert span.parent_id == root.span_id
            assert span.trace_id == root.trace_id
            assert span.attributes['policy'] == 'require-labels'
        assert {s.attributes['rule'] for s in rule_spans} == \
            {'check-app', 'check-team'}
        assert {s.attributes['status'] for s in rule_spans} == \
            {'pass', 'fail'}

    def test_device_scan_span_nests(self, mem):
        from kyverno_tpu.policycache.cache import VALIDATE_ENFORCE
        cache = Cache()
        cache.warm_up([Policy(POLICY)])
        handlers = ResourceHandlers(cache, device=True)
        server = WebhookServer(handlers)
        # scanner builds are async (requests host-loop until ready) —
        # wait so this request takes the device path
        assert handlers.wait_device_ready(cache.get_policies(
            VALIDATE_ENFORCE, 'Pod', 'default'))
        server.handle('/validate/fail', review(pod()))
        [root] = mem.find('webhooks/validate/fail')
        # the async warm-up scan traces its own root span; the request's
        # device scan must nest under the handler span
        scans = mem.find('kyverno/device/scan')
        assert any(s.parent_id == root.span_id for s in scans)

    def test_exception_recorded(self, mem):
        with pytest.raises(ValueError):
            with tracing.start_span('boom'):
                raise ValueError('nope')
        [span] = mem.find('boom')
        assert span.status == 'error' and 'nope' in span.status_message

    def test_noop_without_configure(self):
        tracing.disable()
        with tracing.start_span('x') as s:
            s.set_attribute('a', 1)
        assert tracing.memory_exporter() is None

    def test_otlp_shape(self, mem):
        with tracing.start_span('shape', {'k': 'v'}):
            pass
        [span] = mem.find('shape')
        otlp = span.to_otlp()
        assert otlp['name'] == 'shape'
        assert otlp['attributes'] == [
            {'key': 'k', 'value': {'stringValue': 'v'}}]
        assert int(otlp['endTimeUnixNano']) >= int(
            otlp['startTimeUnixNano'])


class TestExporterHealth:
    def _span(self, tracer, name='s'):
        span = tracing.Span(tracer, name, None)
        span.end()

    def test_jsonl_rotation_keeps_current_plus_one(self, tmp_path):
        """KTPU_TRACE_JSONL_MAX_BYTES: the span file rotates by size —
        current + one rotated generation, every surviving line valid
        JSON."""
        path = tmp_path / 'spans.jsonl'
        exporter = tracing.JsonlExporter(str(path), max_bytes=600)
        tracer = tracing.Tracer([exporter])
        for i in range(40):
            self._span(tracer, f'rotate-{i}')
        exporter.close()
        rotated = tmp_path / 'spans.jsonl.1'
        assert rotated.exists()
        assert {p.name for p in tmp_path.iterdir()} == \
            {'spans.jsonl', 'spans.jsonl.1'}  # exactly one generation
        for p in (path, rotated):
            lines = p.read_text().splitlines()
            assert lines
            for line in lines:
                json.loads(line)
        # newest spans live in the current file
        names = [json.loads(line)['name']
                 for line in path.read_text().splitlines()]
        assert names[-1] == 'rotate-39'

    def test_export_errors_counted_then_exporter_dropped(self):
        """A raising exporter is counted per failure on the cataloged
        error series and dropped after the limit — dead exporters are
        visible, not silent."""
        from kyverno_tpu.observability.metrics import (
            MetricsRegistry, set_global_registry)
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            def broken(span):
                raise RuntimeError('collector gone')
            tracer = tracing.Tracer([broken])
            for _ in range(tracing.EXPORT_FAILURE_LIMIT + 5):
                self._span(tracer)
            assert registry.counter_value(
                tracing.TRACE_EXPORT_ERRORS,
                exporter='function') == tracing.EXPORT_FAILURE_LIMIT
            assert broken not in tracer.exporters
        finally:
            set_global_registry(None)

    def test_jsonl_write_failure_counted(self, tmp_path):
        """A JsonlExporter whose file dies closes itself and the
        tracer counts the failure instead of swallowing it."""
        from kyverno_tpu.observability.metrics import (
            MetricsRegistry, set_global_registry)
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            exporter = tracing.JsonlExporter(str(tmp_path / 'x.jsonl'))
            tracer = tracing.Tracer([exporter])
            self._span(tracer)  # healthy write
            exporter._file.close()  # simulate the fd dying
            self._span(tracer)
            assert registry.counter_value(
                tracing.TRACE_EXPORT_ERRORS,
                exporter='JsonlExporter') == 1
            # closed exporter is now a cheap no-op, not a raiser
            self._span(tracer)
            assert registry.counter_value(
                tracing.TRACE_EXPORT_ERRORS,
                exporter='JsonlExporter') == 1
        finally:
            set_global_registry(None)


class TestProfiling:
    def test_endpoints(self, mem):
        srv = ProfilingServer(port=0)
        port = srv.start()
        try:
            with tracing.start_span('profiled-op'):
                pass
            base = f'http://127.0.0.1:{port}'
            stacks = urllib.request.urlopen(
                f'{base}/debug/pprof/goroutine').read().decode()
            assert 'thread' in stacks
            prof = urllib.request.urlopen(
                f'{base}/debug/pprof/profile?seconds=0.2').read().decode()
            assert prof  # folded stacks or (idle)
            traces = json.loads(urllib.request.urlopen(
                f'{base}/debug/traces').read())
            assert any(s['name'] == 'profiled-op'
                       for s in traces['spans'])
        finally:
            srv.stop()

    def test_setup_flags(self):
        from kyverno_tpu.cmd.internal import Setup
        s = Setup('kyverno', args=['--enable-tracing', '--profile',
                                   '--profile-port', '0'])
        try:
            assert s.profiling_server is not None
            assert tracing.memory_exporter() is not None
        finally:
            if s.profiling_server:
                s.profiling_server.stop()
            tracing.disable()


class TestStreamingSpans:
    def test_early_stopped_stream_records_no_error_spans(self, mem):
        """zip() consumers never exhaust scan_stream; the abandoned
        generator's close must not export error-status spans or leak
        the current-span contextvar into the consumer."""
        import gc
        from kyverno_tpu.compiler.scan import BatchScanner
        from kyverno_tpu.observability import tracing
        scanner = BatchScanner([Policy(POLICY)])
        pods = [pod() for _ in range(6)]
        stream = scanner.scan_stream(pods)
        next(stream)                       # consume one resource
        assert tracing.current_span() is None   # no contextvar leak
        del stream                         # abandon mid-stream
        gc.collect()
        for span in mem.find('kyverno/device/scan'):
            assert span.status != 'error', span.__dict__
