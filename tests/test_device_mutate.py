"""Device-side mutate (kyverno_tpu/mutate/): lowering, kernel
decisions, and the bit-identity contract against the host engine.

The host mutate chain is the oracle: every device-decided row must be
byte-identical to what the engine loop would have produced — statuses,
messages, patches, and the patched document — and every row the device
cannot decide must FALLBACK to that same engine with its reason on the
coverage ledger.  CPU-only, tier-1.
"""

import json

import pytest

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.mutate import (LowerError, MutateScanner,
                                compile_mutate_set, lower_mutate_rule)
from kyverno_tpu.mutate.encode import encode_mutate_batch, exact_milli
from kyverno_tpu.mutate.kernel import (MUT_FALLBACK, MUT_PASS, MUT_SKIP,
                                       MutateKernel)
from kyverno_tpu.observability import coverage


def policy(name, rule):
    return Policy({'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
                   'metadata': {'name': name},
                   'spec': {'rules': [rule]}})


def sm_policy(name, overlay, rule_name='r'):
    return policy(name, {
        'name': rule_name,
        'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
        'mutate': {'patchStrategicMerge': overlay}})


def j6_policy(name, ops, rule_name='r'):
    return policy(name, {
        'name': rule_name,
        'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
        'mutate': {'patchesJson6902': json.dumps(ops)}})


def pod(i=0, **over):
    doc = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': f'p{i}', 'namespace': 'default'},
           'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}
    doc.update(over)
    return doc


def host_chain(policies, doc):
    """The handler's cumulative host mutate loop: ordered
    (policy_name, cells) steps + the final patched document."""
    engine = Engine()
    pctx = PolicyContext(None, new_resource=json.loads(json.dumps(doc)))
    steps = []
    for pol in policies:
        ctx = pctx.copy()
        ctx.policy = pol
        er = engine.mutate(ctx)
        steps.append((pol.name, er))
        if not er.is_successful():
            break
        pctx = pctx.copy()
        pctx.new_resource = er.patched_resource or pctx.new_resource
        pctx.json_context.add_resource(pctx.new_resource)
    return steps, pctx.new_resource


def cells(er):
    return [(r.name, str(r.status), r.message, r.patches)
            for r in er.policy_response.rules]


def assert_identical(policies, docs):
    scanner = MutateScanner(policies)
    assert scanner.ok, [
        (p.rule, p.reason, p.detail) for p in scanner.program.placements]
    rows = scanner.scan([json.loads(json.dumps(d)) for d in docs])
    for doc, (steps, patched) in zip(docs, rows):
        h_steps, h_patched = host_chain(policies, doc)
        # Python semantic equality, the established applier contract:
        # the compiled host fast path (mutate_compile) leaves a leaf
        # whose live value ==-equals the patch constant untouched
        # (3.0 stays 3.0 under an overlay of 3), and generate_patches
        # agrees, so patches/statuses/messages are exact either way
        assert patched == h_patched
        assert len(steps) == len(h_steps)
        for (dpol, der), (hname, her) in zip(steps, h_steps):
            assert dpol.name == hname
            assert cells(der) == cells(her)
    return scanner


# ---------------------------------------------------------------------------
# lowering


class TestLowering:
    def test_strategic_merge_lowers_to_edit_sites(self):
        p = sm_policy('p', {'metadata': {'labels': {'+(team)': 'x'}},
                            'spec': {'dnsPolicy': 'ClusterFirst'}})
        prog = lower_mutate_rule(p.rules[0], 'p')
        assert prog.kind == 'strategic'
        by_path = {s.path: s for s in prog.sites}
        assert by_path[('metadata', 'labels', 'team')].add_only
        assert not by_path[('spec', 'dnsPolicy')].add_only

    def test_json6902_replace_guard(self):
        p = j6_policy('p', [
            {'op': 'add', 'path': '/metadata/labels/a', 'value': 'x'},
            {'op': 'replace', 'path': '/spec/dnsPolicy', 'value': 'None'}])
        prog = lower_mutate_rule(p.rules[0], 'p')
        assert prog.kind == 'json6902'
        by_path = {s.path: s for s in prog.sites}
        assert not by_path[('metadata', 'labels', 'a')].replace
        assert by_path[('spec', 'dnsPolicy')].replace

    @pytest.mark.parametrize('rule,reason', [
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'context': [{'name': 'c', 'configMap': {'name': 'x'}}],
          'mutate': {'patchStrategicMerge': {'metadata': {}}}},
         coverage.REASON_API_CALL),
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'preconditions': {'all': []},
          'mutate': {'patchStrategicMerge': {'metadata': {}}}},
         coverage.REASON_UNSUPPORTED_OPERATOR),
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'mutate': {'foreach': [{'list': 'request.object.spec.containers',
                                  'patchStrategicMerge': {}}]}},
         coverage.REASON_UNSUPPORTED_OPERATOR),
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'mutate': {'targets': [{'kind': 'ConfigMap'}],
                     'patchStrategicMerge': {'metadata': {}}}},
         coverage.REASON_HOST_CLOSURE),
        # roles make the match non-simple: the cumulative chain
        # re-matches per policy, so only kind/ns/op matches lower
        ({'name': 'r', 'match': {'any': [{'subjects': [
            {'kind': 'User', 'name': 'bob'}]}]},
          'mutate': {'patchStrategicMerge': {'metadata': {}}}},
         coverage.REASON_UNSUPPORTED_OPERATOR),
        # null overlay values are RFC-7386 deletes
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'mutate': {'patchStrategicMerge': {
              'metadata': {'labels': {'drop-me': None}}}}},
         coverage.REASON_UNSUPPORTED_OPERATOR),
        # variables leave the static vocabulary
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'mutate': {'patchStrategicMerge': {
              'metadata': {'labels': {'a': '{{request.object.kind}}'}}}}},
         coverage.REASON_UNSUPPORTED_OPERATOR),
        # edits to identity fields could flip later rules' matches
        ({'name': 'r', 'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
          'mutate': {'patchStrategicMerge': {
              'metadata': {'namespace': 'prod'}}}},
         coverage.REASON_UNSUPPORTED_OPERATOR),
    ])
    def test_unlowerable_rules_carry_reasons(self, rule, reason):
        p = policy('p', rule)
        with pytest.raises(LowerError) as ei:
            lower_mutate_rule(p.rules[0], 'p')
        assert ei.value.reason == reason

    def test_set_is_all_or_nothing(self):
        """One unlowerable rule places the whole set on the host (the
        cumulative chain invalidates original-document decisions)."""
        good = sm_policy('good', {'metadata': {'labels': {'a': 'x'}}})
        bad = policy('bad', {
            'name': 'f',
            'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
            'mutate': {'foreach': [{
                'list': 'request.object.spec.containers',
                'patchStrategicMerge': {}}]}})
        prog = compile_mutate_set([good, bad])
        assert not prog.device_ok
        by_rule = {(p.policy, p.rule): p for p in prog.placements}
        assert by_rule[('good', 'r')].placement == coverage.PLACEMENT_HOST
        assert by_rule[('good', 'r')].reason == \
            coverage.REASON_POLICY_COUPLING
        assert by_rule[('bad', 'f')].reason == \
            coverage.REASON_UNSUPPORTED_OPERATOR

    def test_overlapping_edit_sites_conflict(self):
        a = sm_policy('a', {'spec': {'dnsPolicy': 'ClusterFirst'}})
        b = sm_policy('b', {'spec': {'dnsPolicy': 'None'}})
        prog = compile_mutate_set([a, b])
        assert not prog.device_ok
        reasons = {p.reason for p in prog.placements}
        assert coverage.REASON_SITE_CONFLICT in reasons

    def test_prefix_overlap_conflicts_too(self):
        # one rule writes under spec/a, another writes spec/a itself
        a = sm_policy('a', {'spec': {'a': {'b': 'x'}}})
        b = j6_policy('b', [{'op': 'add', 'path': '/spec/a', 'value': 'y'}])
        prog = compile_mutate_set([a, b])
        assert not prog.device_ok

    def test_apply_rules_one_couples(self):
        p = Policy({'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
                    'metadata': {'name': 'one'},
                    'spec': {'applyRules': 'One', 'rules': [
                        {'name': 'r1',
                         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                         'mutate': {'patchStrategicMerge': {
                             'metadata': {'labels': {'a': 'x'}}}}},
                        {'name': 'r2',
                         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                         'mutate': {'patchStrategicMerge': {
                             'metadata': {'labels': {'b': 'y'}}}}}]}})
        prog = compile_mutate_set([p])
        assert not prog.device_ok
        assert all(pl.reason == coverage.REASON_POLICY_COUPLING
                   for pl in prog.placements)


# ---------------------------------------------------------------------------
# kernel decisions


class TestKernel:
    def _one(self, site_policy, doc):
        prog = compile_mutate_set([site_policy])
        assert prog.device_ok
        kernel = MutateKernel(prog)
        lanes = encode_mutate_batch([doc], prog)
        status, edits, reason = kernel(lanes)
        return int(status[0, 0]), int(edits[0, 0]), int(reason[0, 0])

    def test_missing_leaf_applies(self):
        st, ed, _ = self._one(
            sm_policy('p', {'spec': {'dnsPolicy': 'ClusterFirst'}}), pod())
        assert st == MUT_PASS and ed == 1

    def test_equal_value_skips(self):
        st, ed, _ = self._one(
            sm_policy('p', {'spec': {'dnsPolicy': 'ClusterFirst'}}),
            pod(spec={'dnsPolicy': 'ClusterFirst'}))
        assert st == MUT_SKIP and ed == 0

    def test_add_only_skips_present(self):
        st, _, _ = self._one(
            sm_policy('p', {'metadata': {'labels': {'+(t)': 'x'}}}),
            pod(metadata={'name': 'p', 'labels': {'t': 'other'}}))
        assert st == MUT_SKIP

    def test_non_map_intermediate_falls_back(self):
        st, _, rc = self._one(
            sm_policy('p', {'spec': {'a': {'b': 'x'}}}),
            pod(spec={'a': 'not-a-map'}))
        assert st == MUT_FALLBACK and rc != 0

    def test_replace_missing_falls_back(self):
        st, _, _ = self._one(
            j6_policy('p', [{'op': 'replace', 'path': '/spec/tier',
                             'value': 'gold'}]), pod())
        assert st == MUT_FALLBACK

    def test_numeric_outside_milli_window_undecidable(self):
        # 1e300 cannot ride the exact i64 milli lane; equality with the
        # numeric patch constant is undecidable on device
        st, _, _ = self._one(
            sm_policy('p', {'spec': {'replicas': 3}}),
            pod(spec={'replicas': 1e300}))
        assert st == MUT_FALLBACK

    def test_exact_milli_window(self):
        assert exact_milli(True) == 1000
        assert exact_milli(3) == 3000
        assert exact_milli(0.25) == 250
        assert exact_milli(float('inf')) is None
        assert exact_milli(0.1234567) is None  # sub-milli precision
        assert exact_milli((1 << 62)) is None  # overflows ×1000


# ---------------------------------------------------------------------------
# bit-identity against the host engine


class TestBitIdentity:
    def test_strategic_and_json6902_matrix(self):
        policies = [
            sm_policy('labels', {'metadata': {'labels': {
                '+(team)': 'platform', 'stage': 'prod'}}}),
            sm_policy('dns', {'spec': {'dnsPolicy': 'ClusterFirst',
                                       '+(enableServiceLinks)': False}}),
            j6_policy('ann', [
                {'op': 'add', 'path': '/metadata/annotations/managed',
                 'value': 'yes'}]),
        ]
        docs = [
            pod(0),
            pod(1, metadata={'name': 'p1', 'namespace': 'default',
                             'labels': {'team': 'blue', 'stage': 'dev'}}),
            pod(2, metadata={'name': 'p2', 'namespace': 'default',
                             'annotations': {'managed': 'yes'}}),
            pod(3, spec={'dnsPolicy': 'ClusterFirst',
                         'enableServiceLinks': True}),
            pod(4, metadata={'name': 'p4', 'namespace': 'default',
                             'labels': {'stage': 'prod'},
                             'annotations': {'other': 'x'}}),
        ]
        assert_identical(policies, docs)

    def test_fallback_rows_rerun_host_engine(self):
        """A row the kernel cannot decide reruns the faulting policy —
        and every later one — on the engine; output stays identical."""
        policies = [
            j6_policy('rep', [{'op': 'replace', 'path': '/spec/tier',
                               'value': 'gold'}]),
            sm_policy('after', {'metadata': {'labels': {'a': 'x'}}}),
        ]
        docs = [pod(0, spec={'tier': 'bronze'}),   # replace applies
                pod(1)]                            # path missing: FALLBACK
        scanner = assert_identical(policies, docs)
        # the fallback row's engine rerun produced a FAIL on the host
        steps, _ = scanner.scan([json.loads(json.dumps(docs[1]))])[0]
        assert not steps[0][1].is_successful()

    def test_non_map_intermediate_row_identical(self):
        policies = [sm_policy('deep', {'spec': {'a': {'b': 'x'}}})]
        assert_identical(policies, [pod(0, spec={'a': 'scalar'}),
                                    pod(1, spec={'a': {'b': 'x'}}),
                                    pod(2, spec={'a': {'b': 'y'}}),
                                    pod(3, spec={})])

    def test_numeric_and_bool_values_identical(self):
        policies = [sm_policy('num', {'spec': {
            'replicas': 3, '+(hostNetwork)': False}})]
        assert_identical(policies, [
            pod(0, spec={'replicas': 3}),
            pod(1, spec={'replicas': 4}),
            pod(2, spec={'replicas': 3.0}),   # 3.0 == 3 in the milli lane
            pod(3, spec={'hostNetwork': True}),
            pod(4),
        ])

    def test_device_decode_byte_identical_to_host_applier(self):
        """The decode stage IS the compiled host applier: for every row
        the device decides, the patched JSON must be byte-identical to
        ``compile_strategic_merge(...).apply`` on the same document —
        including the numeric-tower case where the applier deliberately
        leaves an ==-equal leaf untouched."""
        from kyverno_tpu.compiler.mutate_compile import \
            compile_strategic_merge
        overlay = {'spec': {'replicas': 3, 'hostNetwork': False}}
        cm = compile_strategic_merge(overlay, 'r', 'num')
        scanner = MutateScanner([sm_policy('num', overlay)])
        assert scanner.ok
        docs = [pod(0, spec={'replicas': 3.0}),
                pod(1, spec={'replicas': 7}),
                pod(2, spec={'replicas': 3, 'hostNetwork': False})]
        rows = scanner.scan([json.loads(json.dumps(d)) for d in docs])
        for doc, (steps, patched) in zip(docs, rows):
            result = cm.apply(json.loads(json.dumps(doc)))
            _status, _msg, changed, host_doc = result
            if changed:
                assert json.dumps(patched, sort_keys=True) == \
                    json.dumps(host_doc, sort_keys=True)
            else:
                assert json.dumps(patched, sort_keys=True) == \
                    json.dumps(doc, sort_keys=True)

    def test_unmatched_namespace_policy_skips(self):
        ns_pol = Policy({'apiVersion': 'kyverno.io/v1', 'kind': 'Policy',
                         'metadata': {'name': 'nsp', 'namespace': 'other'},
                         'spec': {'rules': [{
                             'name': 'r',
                             'match': {'any': [{'resources': {
                                 'kinds': ['Pod']}}]},
                             'mutate': {'patchStrategicMerge': {
                                 'metadata': {'labels': {'x': 'y'}}}}}]}})
        assert_identical([ns_pol], [pod(0)])


# ---------------------------------------------------------------------------
# coverage ledger attribution


class TestCoverageAttribution:
    @pytest.fixture(autouse=True)
    def ledger(self):
        from kyverno_tpu.observability.metrics import MetricsRegistry
        led = coverage.configure(MetricsRegistry())
        yield led
        coverage.disable()

    def test_device_rows_land_as_mutate_path(self, ledger):
        scanner = MutateScanner([
            sm_policy('p', {'metadata': {'labels': {'a': 'x'}}})])
        scanner.scan([pod(0)])
        report = ledger.report()
        recs = [r for r in report['rules'] if r['path'] == 'mutate']
        assert recs and recs[0]['device_rows'] >= 1

    def test_fallback_attributed_with_reason(self, ledger):
        scanner = MutateScanner([
            j6_policy('rep', [{'op': 'replace', 'path': '/spec/tier',
                               'value': 'gold'}])])
        scanner.scan([pod(0)])
        report = ledger.report()
        assert report['fallbacks'].get('mutate', {}).get(
            coverage.REASON_REPLACE_PATH_MISSING, 0) >= 1

    def test_undecidable_reason_recorded(self, ledger):
        scanner = MutateScanner([
            sm_policy('num', {'spec': {'replicas': 3}})])
        scanner.scan([pod(0, spec={'replicas': 1e300})])
        report = ledger.report()
        assert report['fallbacks'].get('mutate', {}).get(
            coverage.REASON_PATCH_UNDECIDABLE, 0) >= 1

    def test_unlowered_set_placements_recorded(self, ledger):
        a = sm_policy('a', {'spec': {'dnsPolicy': 'ClusterFirst'}})
        b = sm_policy('b', {'spec': {'dnsPolicy': 'None'}})
        scanner = MutateScanner([a, b])
        assert not scanner.ok
        report = ledger.report()
        hosts = [r for r in report['rules'] if r['path'] == 'mutate']
        assert hosts and all(
            r['placement'] == coverage.PLACEMENT_HOST for r in hosts)
        assert {r['reason'] for r in hosts} == \
            {coverage.REASON_SITE_CONFLICT}


# ---------------------------------------------------------------------------
# webhook integration (KTPU_MUTATE_DEVICE)


class TestWebhookIntegration:
    @pytest.fixture(scope='class')
    def chain(self):
        from kyverno_tpu.policycache.cache import Cache
        from kyverno_tpu.webhooks.handlers import ResourceHandlers
        from kyverno_tpu.webhooks.server import WebhookServer
        pack = [
            sm_policy('add-labels', {'metadata': {'labels': {
                '+(team)': 'platform'}}}),
            j6_policy('ann', [{'op': 'add',
                               'path': '/metadata/annotations/m',
                               'value': 'y'}]),
        ]
        cache = Cache()
        cache.warm_up(pack)
        handlers = ResourceHandlers(cache)
        server = WebhookServer(handlers)
        yield server, handlers
        handlers.shutdown()

    def _review(self, doc, uid, op='CREATE'):
        return json.dumps({
            'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
            'request': {
                'uid': uid, 'operation': op,
                'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
                'namespace': 'default',
                'name': doc['metadata']['name'], 'object': doc,
                'userInfo': {'username': 'alice'}}}).encode()

    def test_device_mutate_bytes_equal_host_loop(self, chain):
        server, handlers = chain
        from kyverno_tpu.policycache import cache as pcache
        mut = handlers.cache.get_policies(pcache.MUTATE, 'Pod', 'default')
        deadline = __import__('time').time() + 120
        while __import__('time').time() < deadline:
            sc = handlers._device_scanner(mut, kind='mutate')
            if sc is not None:
                break
            __import__('time').sleep(0.02)
        assert sc is not None and sc.ok
        docs = [pod(0), pod(1, metadata={
            'name': 'p1', 'namespace': 'default',
            'labels': {'team': 'red'}, 'annotations': {'m': 'y'}})]
        for op in ('CREATE', 'UPDATE'):
            for i, doc in enumerate(docs):
                handlers.mutate_device = True
                dev = server.handle('/mutate',
                                    self._review(doc, f'd{op}{i}', op))
                handlers.mutate_device = False
                host = server.handle('/mutate',
                                     self._review(doc, f'd{op}{i}', op))
                handlers.mutate_device = True
                assert dev == host

    def test_knob_off_serves_host_loop(self, chain):
        _server, handlers = chain
        handlers.mutate_device = False
        try:
            assert handlers._device_mutate_steps(
                {'operation': 'CREATE'}, None, ['x']) is None
        finally:
            handlers.mutate_device = True

    def test_delete_keeps_host_loop(self, chain):
        _server, handlers = chain
        assert handlers._device_mutate_steps(
            {'operation': 'DELETE'}, None, ['x']) is None
