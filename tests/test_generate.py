"""Generate-rule + UpdateRequest background flow tests
(reference behavior: pkg/background/generate/generate.go,
pkg/webhooks/updaterequest/generator.go)."""

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.background import (
    STATE_COMPLETED, STATE_PENDING, UpdateRequest, UpdateRequestController,
    UpdateRequestGenerator,
)
from kyverno_tpu.background.updaterequest import (
    KYVERNO_NAMESPACE, UR_GENERATE, UR_MUTATE, new_ur_spec,
)
from kyverno_tpu.dclient import FakeClient, NotFoundError
from kyverno_tpu.engine.engine import Engine


GEN_DATA_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: add-networkpolicy
spec:
  generateExistingOnPolicyUpdate: false
  rules:
    - name: default-deny
      match:
        any:
          - resources:
              kinds: [Namespace]
      generate:
        apiVersion: networking.k8s.io/v1
        kind: NetworkPolicy
        name: default-deny
        namespace: "{{request.object.metadata.name}}"
        synchronize: true
        data:
          spec:
            podSelector: {}
            policyTypes: [Ingress, Egress]
"""

CLONE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: sync-secrets
spec:
  rules:
    - name: clone-regcred
      match:
        any:
          - resources:
              kinds: [Namespace]
      generate:
        apiVersion: v1
        kind: Secret
        name: regcred
        namespace: "{{request.object.metadata.name}}"
        synchronize: true
        clone:
          namespace: default
          name: regcred
"""


def _namespace(name):
    return {'apiVersion': 'v1', 'kind': 'Namespace',
            'metadata': {'name': name}}


def _setup(policy_yaml):
    client = FakeClient()
    policy_raw = yaml.safe_load(policy_yaml)
    client.create_resource('kyverno.io/v1', 'ClusterPolicy', '', policy_raw)
    engine = Engine()
    ctrl = UpdateRequestController(client, engine)
    gen = UpdateRequestGenerator(client)
    return client, ctrl, gen


def _enqueue(gen, client, policy_name, trigger, rtype=UR_GENERATE):
    spec = new_ur_spec(rtype, policy_name, trigger)
    return gen.apply(spec)


class TestGenerateData:
    def test_data_rule_creates_target(self):
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        ns = _namespace('apps')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        assert ctrl.process_pending() == 1
        np = client.get_resource('networking.k8s.io/v1', 'NetworkPolicy',
                                 'apps', 'default-deny')
        assert np['spec']['policyTypes'] == ['Ingress', 'Egress']
        labels = np['metadata']['labels']
        assert labels['app.kubernetes.io/managed-by'] == 'kyverno'
        assert labels['kyverno.io/generated-by-kind'] == 'Namespace'
        assert labels['kyverno.io/generated-by-name'] == 'apps'
        assert labels['policy.kyverno.io/synchronize'] == 'enable'

    def test_ur_status_completed_and_generated_resources(self):
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        ns = _namespace('team-a')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        ctrl.process_pending()
        urs = ctrl.list_urs()
        assert len(urs) == 1
        assert urs[0].state == STATE_COMPLETED
        gr = urs[0].generated_resources
        assert gr == [{'apiVersion': 'networking.k8s.io/v1',
                       'kind': 'NetworkPolicy', 'namespace': 'team-a',
                       'name': 'default-deny'}]

    def test_synchronize_updates_drifted_target(self):
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        ns = _namespace('apps')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        ctrl.process_pending()
        # drift the generated resource
        np = client.get_resource('networking.k8s.io/v1', 'NetworkPolicy',
                                 'apps', 'default-deny')
        np['spec']['policyTypes'] = ['Ingress']
        client.update_resource('networking.k8s.io/v1', 'NetworkPolicy',
                               'apps', np)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        ctrl.process_pending()
        np2 = client.get_resource('networking.k8s.io/v1', 'NetworkPolicy',
                                  'apps', 'default-deny')
        assert np2['spec']['policyTypes'] == ['Ingress', 'Egress']

    def test_non_matching_trigger_generates_nothing(self):
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        pod = {'apiVersion': 'v1', 'kind': 'Pod',
               'metadata': {'name': 'p', 'namespace': 'default'}}
        client.create_resource('v1', 'Pod', 'default', pod)
        _enqueue(gen, client, 'add-networkpolicy', pod)
        ctrl.process_pending()
        assert client.list_resource('networking.k8s.io/v1',
                                    'NetworkPolicy') == []


class TestGenerateClone:
    def test_clone_secret_into_new_namespace(self):
        client, ctrl, gen = _setup(CLONE_POLICY)
        client.create_resource('v1', 'Secret', 'default', {
            'apiVersion': 'v1', 'kind': 'Secret',
            'metadata': {'name': 'regcred', 'namespace': 'default'},
            'type': 'kubernetes.io/dockerconfigjson',
            'data': {'.dockerconfigjson': 'e30='},
        })
        ns = _namespace('team-b')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'sync-secrets', ns)
        ctrl.process_pending()
        cloned = client.get_resource('v1', 'Secret', 'team-b', 'regcred')
        assert cloned['data'] == {'.dockerconfigjson': 'e30='}
        assert cloned['type'] == 'kubernetes.io/dockerconfigjson'

    def test_clone_missing_source_fails_ur(self):
        client, ctrl, gen = _setup(CLONE_POLICY)
        ns = _namespace('team-c')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'sync-secrets', ns)
        ctrl.process_pending()
        urs = ctrl.list_urs()
        # retried: stays pending with an error message until MAX_RETRIES
        assert urs[0].state == STATE_PENDING
        assert 'not found' in urs[0].status.get('message', '')


class TestDownstreamCleanup:
    def test_fresh_ur_for_retired_trigger_deletes_by_labels(self):
        """A new UR (empty status) whose trigger no longer matches must
        still locate and delete downstream resources via ownership labels
        (reference: generate.go deleteDownstream by label query)."""
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        ns = _namespace('apps')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        ctrl.process_pending()
        ctrl.cleanup_completed()  # drop the completed UR and its status
        client.get_resource('networking.k8s.io/v1', 'NetworkPolicy',
                            'apps', 'default-deny')
        # retire the trigger: DELETE operation with oldObject matching
        spec = new_ur_spec(UR_GENERATE, 'add-networkpolicy', ns,
                           admission_request={'operation': 'DELETE',
                                              'oldObject': ns},
                           operation='DELETE')
        client.delete_resource('v1', 'Namespace', '', 'apps')
        gen.apply(spec)
        ctrl.process_pending()
        assert client.list_resource('networking.k8s.io/v1',
                                    'NetworkPolicy') == []


class TestURGenerator:
    def test_dedupes_pending_by_labels(self):
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        ns = _namespace('apps')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        urs = client.list_resource('kyverno.io/v1beta1', 'UpdateRequest',
                                   KYVERNO_NAMESPACE)
        assert len(urs) == 1

    def test_cleanup_completed(self):
        client, ctrl, gen = _setup(GEN_DATA_POLICY)
        ns = _namespace('apps')
        client.create_resource('v1', 'Namespace', '', ns)
        _enqueue(gen, client, 'add-networkpolicy', ns)
        ctrl.process_pending()
        assert ctrl.cleanup_completed() == 1
        assert ctrl.list_urs() == []


class TestMutateExisting:
    POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: label-configmaps
spec:
  rules:
    - name: stamp
      match:
        any:
          - resources:
              kinds: [ConfigMap]
      mutate:
        targets:
          - apiVersion: v1
            kind: ConfigMap
            name: app-config
            namespace: default
        patchStrategicMerge:
          metadata:
            labels:
              stamped: "true"
"""

    def test_mutate_existing_target(self):
        client, ctrl, gen = _setup(self.POLICY)
        cm = {'apiVersion': 'v1', 'kind': 'ConfigMap',
              'metadata': {'name': 'app-config', 'namespace': 'default'},
              'data': {'k': 'v'}}
        client.create_resource('v1', 'ConfigMap', 'default', cm)
        trigger = {'apiVersion': 'v1', 'kind': 'ConfigMap',
                   'metadata': {'name': 'trigger', 'namespace': 'default'}}
        client.create_resource('v1', 'ConfigMap', 'default', trigger)
        _enqueue(gen, client, 'label-configmaps', trigger, UR_MUTATE)
        ctrl.process_pending()
        urs = ctrl.list_urs()
        assert urs[0].state == STATE_COMPLETED, urs[0].status
        patched = client.get_resource('v1', 'ConfigMap', 'default',
                                      'app-config')
        assert patched['metadata']['labels']['stamped'] == 'true'
        assert patched['data'] == {'k': 'v'}

    def test_non_matching_trigger_leaves_targets_alone(self):
        """The trigger must select the rule before any target is touched
        (reference: mutate.go ProcessUR -> engine.Mutate rule gating)."""
        raw = yaml.safe_load(self.POLICY)
        raw['spec']['rules'][0]['match'] = {'any': [{'resources': {
            'kinds': ['Pod'], 'names': ['must-be-this']}}]}
        client, ctrl, gen = _setup(yaml.dump(raw))
        cm = {'apiVersion': 'v1', 'kind': 'ConfigMap',
              'metadata': {'name': 'app-config', 'namespace': 'default'},
              'data': {'k': 'v'}}
        client.create_resource('v1', 'ConfigMap', 'default', cm)
        trigger = {'apiVersion': 'v1', 'kind': 'Pod',
                   'metadata': {'name': 'other', 'namespace': 'default'},
                   'spec': {'containers': [{'name': 'c', 'image': 'i'}]}}
        client.create_resource('v1', 'Pod', 'default', trigger)
        _enqueue(gen, client, 'label-configmaps', trigger, UR_MUTATE)
        ctrl.process_pending()
        urs = ctrl.list_urs()
        assert urs[0].state == STATE_COMPLETED, urs[0].status
        untouched = client.get_resource('v1', 'ConfigMap', 'default',
                                        'app-config')
        assert 'labels' not in untouched['metadata']

    def test_failing_preconditions_leave_targets_alone(self):
        raw = yaml.safe_load(self.POLICY)
        raw['spec']['rules'][0]['preconditions'] = {
            'all': [{'key': '{{request.object.metadata.name}}',
                     'operator': 'Equals', 'value': 'only-this'}]}
        client, ctrl, gen = _setup(yaml.dump(raw))
        cm = {'apiVersion': 'v1', 'kind': 'ConfigMap',
              'metadata': {'name': 'app-config', 'namespace': 'default'},
              'data': {'k': 'v'}}
        client.create_resource('v1', 'ConfigMap', 'default', cm)
        trigger = {'apiVersion': 'v1', 'kind': 'ConfigMap',
                   'metadata': {'name': 'trigger', 'namespace': 'default'}}
        client.create_resource('v1', 'ConfigMap', 'default', trigger)
        _enqueue(gen, client, 'label-configmaps', trigger, UR_MUTATE)
        ctrl.process_pending()
        urs = ctrl.list_urs()
        assert urs[0].state == STATE_COMPLETED, urs[0].status
        untouched = client.get_resource('v1', 'ConfigMap', 'default',
                                        'app-config')
        assert 'labels' not in untouched['metadata']


class TestBackgroundFilter:
    def test_filter_reports_pass_for_matching_generate_rule(self):
        from kyverno_tpu.engine.api import PolicyContext, RuleStatus
        policy = Policy(yaml.safe_load(GEN_DATA_POLICY))
        engine = Engine()
        pctx = PolicyContext(policy=policy, new_resource=_namespace('x'))
        resp = engine.filter_background_rules(pctx)
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.PASS]

    def test_filter_skips_when_preconditions_fail(self):
        from kyverno_tpu.engine.api import PolicyContext, RuleStatus
        raw = yaml.safe_load(GEN_DATA_POLICY)
        raw['spec']['rules'][0]['preconditions'] = {
            'all': [{'key': '{{request.object.metadata.name}}',
                     'operator': 'Equals', 'value': 'only-this'}]}
        engine = Engine()
        pctx = PolicyContext(policy=Policy(raw), new_resource=_namespace('x'))
        resp = engine.filter_background_rules(pctx)
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.SKIP]
