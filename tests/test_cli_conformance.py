"""Golden conformance: run the reference CLI test corpus end-to-end.

Reference: test/cli/test — 55 kyverno-test.yaml fixtures exercising
foreach, preconditions, subresources, autogen, context entries, wildcard
matching, mutation overlays, generation, manifest signatures, etc.
(SURVEY.md §4 names this corpus the behavioral conformance suite).

The fixtures are consumed in place from the read-only reference checkout;
nothing is copied. Tests are skipped when the reference tree is absent.
"""

import os

import pytest

REFERENCE_CORPUS = '/root/reference/test/cli/test'

# These fixture dirs verify cosign image signatures against live OCI
# registries (ghcr.io) — the reference CI runs them with network access;
# they cannot work in a hermetic environment.
# keys are fixture ids (relative dir under test/cli) — see _fixture_id
NETWORK_BOUND = {
    'test/images/digest',          # digest fetch from ghcr.io
    'test/images/signatures',      # cosign verification against ghcr.io
    'test/images/secure-images',
    'test/images/verify-signature',
}


REFERENCE_MUTATE_CORPUS = '/root/reference/test/cli/test-mutate'
REFERENCE_GENERATE_CORPUS = '/root/reference/test/cli/test-generate'
REFERENCE_FAIL_CORPUS = '/root/reference/test/cli/test-fail'


def _find_fixtures():
    from kyverno_tpu.cli.test_command import find_test_files
    out = []
    for corpus in (REFERENCE_CORPUS, REFERENCE_MUTATE_CORPUS,
                   REFERENCE_GENERATE_CORPUS):
        if os.path.isdir(corpus):
            out.extend(find_test_files(corpus))
    return out


FIXTURES = _find_fixtures()


def _fixture_id(path):
    return os.path.relpath(os.path.dirname(path), '/root/reference/test/cli')


@pytest.mark.skipif(not FIXTURES, reason='reference corpus not available')
@pytest.mark.parametrize('fixture', FIXTURES, ids=_fixture_id)
def test_reference_cli_fixture(fixture):
    from kyverno_tpu.cli.test_command import run_test_file
    # skip decided by the fixture's directory, not by matching failure
    # strings — a regression in a policy whose name happens to contain a
    # network-bound substring must still fail loudly
    fixture_dir = _fixture_id(fixture)
    if fixture_dir in NETWORK_BOUND:
        pytest.skip(f'{fixture_dir}: requires registry network access')
    name, rows = run_test_file(fixture)
    failed = []
    for row in rows:
        if not row.ok:
            key = f'{row.policy}/{row.rule}/{row.resource}'
            failed.append(f'{key}: expected {row.expected}, got {row.actual}')
    if failed:
        raise AssertionError(
            f'{name}: {len(failed)}/{len(rows)} rows diverged:\n  ' +
            '\n  '.join(failed))


# reference: .github/workflows/cli.yaml:45-47 — these fixtures must make
# `kyverno test` exit non-zero (missing policy/rule/resource rows diverge)
EXPECTED_FAIL_DIRS = ['missing-policy', 'missing-rule', 'missing-resource']


@pytest.mark.skipif(not os.path.isdir(REFERENCE_FAIL_CORPUS),
                    reason='reference corpus not available')
@pytest.mark.parametrize('subdir', EXPECTED_FAIL_DIRS)
def test_reference_cli_expected_failures(subdir):
    from kyverno_tpu.cli.test_command import find_test_files, run_test_file
    files = find_test_files(os.path.join(REFERENCE_FAIL_CORPUS, subdir))
    assert files, f'no fixtures under {subdir}'
    _, rows = run_test_file(files[0])
    assert any(not row.ok for row in rows),         f'{subdir}: expected at least one diverging row'
