"""Multi-process jax.distributed validation (SURVEY §5.8).

The single-host virtual mesh (conftest's 8 CPU devices) exercises the
sharding math; this test exercises the actual multi-HOST path: two
separate processes join one jax.distributed coordination service, form
a global mesh spanning both, run the sharded scan step on the same
batch, and must agree on the psum-reduced verdict summary — exactly how
a v5e multi-host slice runs (one process per host, collectives over
the global mesh).  Process 0 is the convention leader
(controllers/leaderelection.py mesh_is_leader).
"""

import json
import os
import socket
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import json, os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update('jax_platforms', 'cpu')
jax.distributed.initialize(coordinator_address=%(coord)r,
                           num_processes=2,
                           process_id=int(sys.argv[1]))
assert jax.process_count() == 2
import numpy as np
import bench
from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.parallel.mesh import distributed_scan_step, make_mesh
from kyverno_tpu.controllers.leaderelection import mesh_is_leader

policies = load_policies_from_yaml(bench.PACK)
cps = compile_policies(policies)
import random
rng = random.Random(0)
resources = [bench.make_pod(rng, i) for i in range(24)]
mesh = make_mesh()   # global devices across both processes
assert mesh.devices.size == jax.device_count() == 4  # 2 per process
statuses, summary = distributed_scan_step(cps, mesh, resources)

# streamed REPORT path across the same multi-host mesh: >= 3 chunks
# (KTPU_SCAN_CHUNK=16 over 40 resources), reports must be identical on
# every host and equal to the single-process run (timestamps pinned)
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.reports.results import set_responses
from kyverno_tpu.reports.types import new_background_scan_report
stream_resources = [bench.make_pod(rng, 1000 + i) for i in range(40)]
scanner = BatchScanner(policies, mesh=mesh)
report_dump = []
for resource, responses in zip(stream_resources,
                               scanner.scan_stream(stream_resources)):
    report = new_background_scan_report(resource)
    relevant = [r for r in responses if r.policy_response.rules]
    set_responses(report, *relevant, now=0)
    # result dicts are shared flyweights: sanitize into copies
    from kyverno_tpu.reports.results import get_results
    report.setdefault('spec', {})['results'] = [
        {k: v for k, v in res.items() if k != 'timestamp'}
        for res in get_results(report)]
    report_dump.append(report)
import hashlib
report_hash = hashlib.sha256(
    json.dumps(report_dump, sort_keys=True).encode()).hexdigest()

print('RESULT ' + json.dumps({
    'process': jax.process_index(),
    'leader': mesh_is_leader(),
    'devices': jax.device_count(),
    'local_devices': jax.local_device_count(),
    'summary': np.asarray(summary).tolist(),
    'status_sum': int(np.asarray(statuses).sum()),
    'n_stream_reports': len(report_dump),
    'report_hash': report_hash,
}))
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def test_two_process_distributed_scan_agrees():
    coord = f'127.0.0.1:{_free_port()}'
    code = WORKER % {'repo': REPO, 'coord': coord}
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
    env['KTPU_SCAN_CHUNK'] = '16'   # 40 resources -> 3 streamed chunks
    env.pop('JAX_NUM_PROCESSES', None)
    procs = [subprocess.Popen([sys.executable, '-c', code, str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=540)
        assert p.returncode == 0, f'worker failed:\n{err[-3000:]}'
        [line] = [ln for ln in out.splitlines() if ln.startswith('RESULT ')]
        outs.append(json.loads(line[len('RESULT '):]))
    by_proc = {o['process']: o for o in outs}
    assert set(by_proc) == {0, 1}
    # process-0 leader convention, 2 local / 4 global devices each
    assert by_proc[0]['leader'] is True
    assert by_proc[1]['leader'] is False
    for o in outs:
        assert o['devices'] == 4 and o['local_devices'] == 2
    # the psum-reduced verdict summary is identical on every process,
    # and both processes reconstruct identical full status matrices
    assert by_proc[0]['summary'] == by_proc[1]['summary']
    assert by_proc[0]['status_sum'] == by_proc[1]['status_sum']
    # the streamed report path ran >= 3 chunks and produced identical
    # reports on both hosts
    assert by_proc[0]['n_stream_reports'] == 40
    assert by_proc[0]['report_hash'] == by_proc[1]['report_hash']

    # ground truth: the same batch on a single-process evaluator
    import random

    import numpy as np

    import bench
    from kyverno_tpu.api.policy import load_policies_from_yaml
    from kyverno_tpu.compiler.compile import compile_policies
    from kyverno_tpu.compiler.encode import encode_batch
    from kyverno_tpu.ops.eval import build_evaluator, shard_batch

    policies = load_policies_from_yaml(bench.PACK)
    cps = compile_policies(policies)
    rng = random.Random(0)
    resources = [bench.make_pod(rng, i) for i in range(24)]
    batch = encode_batch(resources, cps, padded_n=24)
    t, layout = shard_batch(batch.tensors(), None)
    evaluator = build_evaluator(cps)
    s, d, fd = evaluator(t, layout)
    assert int(np.asarray(s).sum()) == by_proc[0]['status_sum']

    # single-process ground truth for the streamed report path
    import hashlib
    import json as _json

    from kyverno_tpu.compiler.scan import BatchScanner
    from kyverno_tpu.reports.results import set_responses
    from kyverno_tpu.reports.types import new_background_scan_report

    stream_resources = [bench.make_pod(rng, 1000 + i) for i in range(40)]
    scanner = BatchScanner(policies)
    dump = []
    for resource, responses in zip(stream_resources,
                                   scanner.scan_stream(stream_resources)):
        report = new_background_scan_report(resource)
        relevant = [r for r in responses if r.policy_response.rules]
        set_responses(report, *relevant, now=0)
        from kyverno_tpu.reports.results import get_results
        report.setdefault('spec', {})['results'] = [
            {k: v for k, v in res.items() if k != 'timestamp'}
            for res in get_results(report)]
        dump.append(report)
    want = hashlib.sha256(
        _json.dumps(dump, sort_keys=True).encode()).hexdigest()
    assert want == by_proc[0]['report_hash']
