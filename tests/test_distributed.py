"""Multi-process jax.distributed validation (SURVEY §5.8).

The single-host virtual mesh (conftest's 8 CPU devices) exercises the
sharding math; this test exercises the actual multi-HOST path: two
separate processes join one jax.distributed coordination service, form
a global mesh spanning both, run the sharded scan step on the same
batch, and must agree on the psum-reduced verdict summary — exactly how
a v5e multi-host slice runs (one process per host, collectives over
the global mesh).  Process 0 is the convention leader
(controllers/leaderelection.py mesh_is_leader).
"""

import json
import os
import socket
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import json, os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update('jax_platforms', 'cpu')
jax.distributed.initialize(coordinator_address=%(coord)r,
                           num_processes=2,
                           process_id=int(sys.argv[1]))
assert jax.process_count() == 2
import numpy as np
import bench
from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.parallel.mesh import distributed_scan_step, make_mesh
from kyverno_tpu.controllers.leaderelection import mesh_is_leader

policies = load_policies_from_yaml(bench.PACK)
cps = compile_policies(policies)
import random
rng = random.Random(0)
resources = [bench.make_pod(rng, i) for i in range(24)]
mesh = make_mesh()   # global devices across both processes
assert mesh.devices.size == jax.device_count() == 4  # 2 per process
statuses, summary = distributed_scan_step(cps, mesh, resources)

# streamed REPORT path across the same multi-host mesh: >= 3 chunks
# (KTPU_SCAN_CHUNK=16 over 40 resources), reports must be identical on
# every host and equal to the single-process run (timestamps pinned)
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.reports.results import set_responses
from kyverno_tpu.reports.types import new_background_scan_report
stream_resources = [bench.make_pod(rng, 1000 + i) for i in range(40)]
scanner = BatchScanner(policies, mesh=mesh)
report_dump = []
for resource, responses in zip(stream_resources,
                               scanner.scan_stream(stream_resources)):
    report = new_background_scan_report(resource)
    relevant = [r for r in responses if r.policy_response.rules]
    set_responses(report, *relevant, now=0)
    # result dicts are shared flyweights: sanitize into copies
    from kyverno_tpu.reports.results import get_results
    report.setdefault('spec', {})['results'] = [
        {k: v for k, v in res.items() if k != 'timestamp'}
        for res in get_results(report)]
    report_dump.append(report)
import hashlib
report_hash = hashlib.sha256(
    json.dumps(report_dump, sort_keys=True).encode()).hexdigest()

print('RESULT ' + json.dumps({
    'process': jax.process_index(),
    'leader': mesh_is_leader(),
    'devices': jax.device_count(),
    'local_devices': jax.local_device_count(),
    'summary': np.asarray(summary).tolist(),
    'status_sum': int(np.asarray(statuses).sum()),
    'n_stream_reports': len(report_dump),
    'report_hash': report_hash,
}))
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def test_two_process_distributed_scan_agrees():
    coord = f'127.0.0.1:{_free_port()}'
    code = WORKER % {'repo': REPO, 'coord': coord}
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
    env['KTPU_SCAN_CHUNK'] = '16'   # 40 resources -> 3 streamed chunks
    env.pop('JAX_NUM_PROCESSES', None)
    procs = [subprocess.Popen([sys.executable, '-c', code, str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=540)
        assert p.returncode == 0, f'worker failed:\n{err[-3000:]}'
        [line] = [ln for ln in out.splitlines() if ln.startswith('RESULT ')]
        outs.append(json.loads(line[len('RESULT '):]))
    by_proc = {o['process']: o for o in outs}
    assert set(by_proc) == {0, 1}
    # process-0 leader convention, 2 local / 4 global devices each
    assert by_proc[0]['leader'] is True
    assert by_proc[1]['leader'] is False
    for o in outs:
        assert o['devices'] == 4 and o['local_devices'] == 2
    # the psum-reduced verdict summary is identical on every process,
    # and both processes reconstruct identical full status matrices
    assert by_proc[0]['summary'] == by_proc[1]['summary']
    assert by_proc[0]['status_sum'] == by_proc[1]['status_sum']
    # the streamed report path ran >= 3 chunks and produced identical
    # reports on both hosts
    assert by_proc[0]['n_stream_reports'] == 40
    assert by_proc[0]['report_hash'] == by_proc[1]['report_hash']

    # ground truth: the same batch on a single-process evaluator
    import random

    import numpy as np

    import bench
    from kyverno_tpu.api.policy import load_policies_from_yaml
    from kyverno_tpu.compiler.compile import compile_policies
    from kyverno_tpu.compiler.encode import encode_batch
    from kyverno_tpu.ops.eval import build_evaluator, shard_batch

    policies = load_policies_from_yaml(bench.PACK)
    cps = compile_policies(policies)
    rng = random.Random(0)
    resources = [bench.make_pod(rng, i) for i in range(24)]
    batch = encode_batch(resources, cps, padded_n=24)
    t, layout = shard_batch(batch.tensors(), None)
    evaluator = build_evaluator(cps)
    s, d, fd = evaluator(t, layout)
    assert int(np.asarray(s).sum()) == by_proc[0]['status_sum']

    # single-process ground truth for the streamed report path
    import hashlib
    import json as _json

    from kyverno_tpu.compiler.scan import BatchScanner
    from kyverno_tpu.reports.results import set_responses
    from kyverno_tpu.reports.types import new_background_scan_report

    stream_resources = [bench.make_pod(rng, 1000 + i) for i in range(40)]
    scanner = BatchScanner(policies)
    dump = []
    for resource, responses in zip(stream_resources,
                                   scanner.scan_stream(stream_resources)):
        report = new_background_scan_report(resource)
        relevant = [r for r in responses if r.policy_response.rules]
        set_responses(report, *relevant, now=0)
        from kyverno_tpu.reports.results import get_results
        report.setdefault('spec', {})['results'] = [
            {k: v for k, v in res.items() if k != 'timestamp'}
            for res in get_results(report)]
        dump.append(report)
    want = hashlib.sha256(
        _json.dumps(dump, sort_keys=True).encode()).hexdigest()
    assert want == by_proc[0]['report_hash']


# -- fleet observatory on the virtual mesh (ISSUE 18) -------------------------
#
# Mesh-step telemetry, straggler blame and federation against the
# conftest 8-device mesh: the KTPU_FLEET=0 path must be bit-identical,
# an injected per-shard delay must be *named* as the straggler, and the
# /debug/fleet endpoint must agree with the offline CLI merge.

import time as _time

import numpy as np
import pytest
import yaml

from kyverno_tpu import faults
from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.observability import fleet
from kyverno_tpu.observability.metrics import MetricsRegistry
from kyverno_tpu.parallel.mesh import distributed_scan_step, make_mesh

FLEET_PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: fleet-pack
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: no-latest
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: no latest
        pattern:
          spec:
            containers:
              - image: "!*:latest"
"""


def _fleet_pods(n):
    return [{'apiVersion': 'v1', 'kind': 'Pod',
             'metadata': {'name': f'p{i}'},
             'spec': {'containers': [
                 {'name': 'c',
                  'image': 'nginx:latest' if i % 2 else 'nginx:1.25'}]}}
            for i in range(n)]


@pytest.fixture
def mesh8():
    import jax
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip('needs the 8-device virtual mesh')
    return make_mesh(devices[:8])


@pytest.fixture
def fleet_teardown():
    yield
    fleet.disable()
    faults.disable()


def _fleet_cps():
    return compile_policies(
        [Policy(d) for d in yaml.safe_load_all(FLEET_PACK) if d])


class TestFleetMesh:
    def test_ktpu_fleet_0_bit_identity(self, mesh8, monkeypatch,
                                       fleet_teardown):
        cps = _fleet_cps()
        resources = _fleet_pods(13)
        fleet.disable()
        base_s, base_sum = distributed_scan_step(cps, mesh8, resources)
        # KTPU_FLEET=0 refuses configuration outright
        monkeypatch.setenv('KTPU_FLEET', '0')
        assert fleet.configure(MetricsRegistry()) is None
        assert not fleet.enabled()
        off_s, off_sum = distributed_scan_step(cps, mesh8, resources)
        # armed: same outputs, telemetry on the side
        monkeypatch.delenv('KTPU_FLEET')
        reg = MetricsRegistry()
        assert fleet.configure(
            reg, profile_trigger=lambda: None) is not None
        on_s, on_sum = distributed_scan_step(cps, mesh8, resources)
        np.testing.assert_array_equal(base_s, off_s)
        np.testing.assert_array_equal(base_sum, off_sum)
        np.testing.assert_array_equal(base_s, on_s)
        np.testing.assert_array_equal(base_sum, on_sum)
        snap = reg.snapshot(fleet.identity())
        assert fleet.MESH_COLLECTIVE_SECONDS in snap['counters']
        assert fleet.MESH_PADDING_ROWS in snap['counters']
        assert fleet.MESH_STEP_DURATION in snap['hists']
        # per-shard series (0..7) plus the shard=all whole-step series
        shards = {dict(key)['shard'] for key, *_rest
                  in snap['hists'][fleet.MESH_STEP_DURATION]['series']
                  for key in [tuple(map(tuple, key))]}
        assert shards == {str(i) for i in range(8)} | {'all'}

    def test_injected_delay_names_straggler(self, mesh8,
                                            fleet_teardown):
        cps = _fleet_cps()
        resources = _fleet_pods(16)
        fleet.disable()
        distributed_scan_step(cps, mesh8, resources)  # compile warm
        fired = []
        reg = MetricsRegistry()
        fleet.configure(reg, window=2,
                        profile_trigger=lambda: fired.append(1))
        # 8 mesh_shard checks per step, batch-axis order: the 3rd and
        # 11th checks are shard 2 of steps 1 and 2 — a sustained
        # straggler on shard 2 across the whole window
        faults.configure('site=mesh_shard,nth=3,delay_ms=150;'
                         'site=mesh_shard,nth=11,delay_ms=150')
        try:
            distributed_scan_step(cps, mesh8, resources)
            distributed_scan_step(cps, mesh8, resources)
        finally:
            faults.disable()
        verdict = fleet.analyzer().verdict()
        assert verdict['slow_shard'] == 2
        assert verdict['sustained'] is True
        assert verdict['bound_by'] == 'straggler'
        assert 'shard 2' in verdict['note']
        assert verdict['device']  # names the blamed device
        assert verdict['skew'] > 2.0
        # the deep-profile trigger fires exactly once (rate-limited,
        # single-fire on the False->True transition), on a worker
        # thread — wait for it
        deadline = _time.monotonic() + 5.0
        while not fired and _time.monotonic() < deadline:
            _time.sleep(0.02)
        assert fired == [1]
        # the skew gauge carries the mesh identity label
        assert reg.gauge_value(fleet.MESH_SHARD_SKEW,
                               mesh='data8') > 2.0

    def test_endpoint_and_cli_agree(self, mesh8, tmp_path,
                                    fleet_teardown):
        import subprocess
        import urllib.request
        from kyverno_tpu.observability.profiling import ProfilingServer
        cps = _fleet_cps()
        reg = MetricsRegistry()
        fr = fleet.configure(reg, profile_trigger=lambda: None)
        distributed_scan_step(cps, mesh8, _fleet_pods(9))
        srv = ProfilingServer(port=0)
        srv.start()
        try:
            url = f'http://127.0.0.1:{srv.port}/debug/fleet'
            with urllib.request.urlopen(url, timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            with urllib.request.urlopen(url + '?format=table',
                                        timeout=10) as resp:
                table = resp.read().decode()
        finally:
            srv.stop()
        assert doc['enabled'] is True
        assert doc['skew'] is not None
        assert 'merged counter' in table
        endpoint_totals = fleet.FleetRegistry.counter_totals(
            doc['merged'])
        # offline CLI over the JSONL snapshot artifact must agree
        snap_path = tmp_path / 'host0.jsonl'
        fleet.write_snapshot(str(snap_path), reg)
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, 'scripts', 'fleet_report.py'),
             '--json', str(snap_path)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr[-2000:]
        cli_doc = json.loads(out.stdout)
        cli_totals = fleet.FleetRegistry.counter_totals(
            cli_doc['merged'])
        for name in set(endpoint_totals) | set(cli_totals):
            assert cli_totals.get(name) == pytest.approx(
                endpoint_totals.get(name)), name
        assert fr.report()['processes']
