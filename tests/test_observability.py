"""Observability: metrics instruments, event generation, structured logs
(reference: pkg/metrics, pkg/event, pkg/logging)."""

import json
import logging

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.observability.events import (EventGenerator,
                                              events_for_response)
from kyverno_tpu.observability.metrics import (POLICY_RESULTS,
                                               MetricsRegistry,
                                               record_policy_results)

POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: m
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: audit
  rules:
    - name: r
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: needs team
        pattern: {metadata: {labels: {team: "?*"}}}
""")


def run_engine(labels):
    pod = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': 'p', 'namespace': 'd', 'labels': labels},
           'spec': {}}
    return Engine().validate(PolicyContext(Policy(POLICY),
                                           new_resource=pod))


class TestMetrics:
    def test_policy_results_counter(self):
        reg = MetricsRegistry()
        record_policy_results(reg, run_engine({}), 'CREATE')
        record_policy_results(reg, run_engine({'team': 'x'}), 'CREATE')
        assert reg.counter_total(POLICY_RESULTS) == 2
        assert reg.counter_value(
            POLICY_RESULTS, policy_name='m', rule_name='r',
            rule_result='fail', rule_type='Validation',
            resource_kind='Pod', resource_namespace='d',
            resource_request_operation='create') == 1
        text = reg.render()
        assert '# TYPE kyverno_policy_results_total counter' in text
        assert 'rule_result="pass"' in text
        assert 'kyverno_policy_execution_duration_seconds_bucket' in text

    def test_disable(self):
        reg = MetricsRegistry(disabled=[POLICY_RESULTS])
        record_policy_results(reg, run_engine({}), 'CREATE')
        assert reg.counter_total(POLICY_RESULTS) == 0

    def test_zero_gauge_stays_visible(self):
        """set_gauge(0) must keep the series in exposition — a vanished
        series reads as 'target gone', not 'value is zero'."""
        reg = MetricsRegistry()
        reg.set_gauge('kyverno_policy_rule_info_total', 1.0, rule='r')
        reg.set_gauge('kyverno_policy_rule_info_total', 0.0, rule='r')
        text = reg.render()
        assert 'kyverno_policy_rule_info_total{rule="r"} 0' in text
        assert reg.gauge_value('kyverno_policy_rule_info_total',
                               rule='r') == 0.0

    def test_clear_gauge_removes_series(self):
        reg = MetricsRegistry()
        reg.set_gauge('kyverno_policy_rule_info_total', 1.0, rule='r')
        reg.clear_gauge('kyverno_policy_rule_info_total', rule='r')
        assert 'rule="r"' not in reg.render()
        # clearing an unknown series is a no-op
        reg.clear_gauge('kyverno_policy_rule_info_total', rule='ghost')

    def test_residency_gauges_reset_on_close(self):
        """Marked residency gauges (queue depth, breaker state,
        in-flight chunks) sweep to 0 on close; a drained server must
        scrape as empty, not as its last sampled occupancy.  Unmarked
        gauges keep their value; the series stays visible."""
        reg = MetricsRegistry()
        reg.set_gauge('kyverno_tpu_admission_queue_depth', 7.0)
        reg.set_gauge('kyverno_tpu_breaker_state', 2.0, state='open')
        reg.set_gauge('kyverno_tpu_device_batch_size', 64.0)
        reg.mark_reset_on_close('kyverno_tpu_admission_queue_depth')
        reg.mark_reset_on_close('kyverno_tpu_breaker_state')
        reg.mark_reset_on_close('never_written_gauge')  # tolerated
        reg.reset_residency_gauges()
        assert reg.gauge_value(
            'kyverno_tpu_admission_queue_depth') == 0.0
        # every label series of a marked name sweeps
        assert reg.gauge_value('kyverno_tpu_breaker_state',
                               state='open') == 0.0
        # non-residency gauges keep their last value
        assert reg.gauge_value('kyverno_tpu_device_batch_size') == 64.0
        # swept, not retracted: the 0 stays in exposition
        assert 'kyverno_tpu_admission_queue_depth 0' in reg.render()

    def test_serving_layers_mark_their_residency_gauges(self):
        """The batcher, breaker board, and device pipeline each mark
        their occupancy gauge at registration time — the shutdown
        sweep in cmd/internal.Setup depends on it."""
        from kyverno_tpu.observability import device as devtel
        from kyverno_tpu.observability.metrics import set_global_registry
        from kyverno_tpu.serving.batcher import (QUEUE_DEPTH,
                                                 AdmissionBatcher)
        from kyverno_tpu.serving.breaker import (BREAKER_STATE,
                                                 BreakerRegistry)
        reg = MetricsRegistry()
        set_global_registry(reg)
        try:
            devtel.configure(reg)
            batcher = AdmissionBatcher(window_ms=1, max_batch=1,
                                       queue_cap=1)
            batcher._registry()
            batcher.stop()
            BreakerRegistry(failure_limit=1).record_failure(
                ('fp',), [], 'boom')
        finally:
            set_global_registry(None)
            devtel.disable()
        assert {QUEUE_DEPTH, BREAKER_STATE,
                devtel.PIPELINE_INFLIGHT} <= reg._reset_on_close

    def test_histogram_bucket_override(self):
        """Compile-scale samples (43-49s fresh-cache compiles) must land
        in real buckets, not +Inf — per-histogram overrides up to 120s."""
        from kyverno_tpu.observability.metrics import WIDE_BUCKETS
        reg = MetricsRegistry()
        name = 'kyverno_tpu_scan_stage_duration_seconds'
        reg.register_histogram(name, WIDE_BUCKETS)
        reg.observe(name, 45.0, stage='compile')
        text = reg.render()
        assert 'le="60"' in text and 'le="120"' in text
        # the 45s sample is inside the 60s and 120s buckets
        assert f'{name}_bucket{{stage="compile",le="60"}} 1' in text
        assert f'{name}_bucket{{stage="compile",le="120"}} 1' in text
        assert f'{name}_bucket{{stage="compile",le="30"}} 0' in text
        assert WIDE_BUCKETS[-1] >= 120.0

    def test_bucket_override_ignored_after_first_sample(self):
        reg = MetricsRegistry()
        reg.observe('kyverno_admission_review_duration_seconds', 0.2)
        # too late: series already sized on the default buckets
        reg.register_histogram(
            'kyverno_admission_review_duration_seconds', (1.0, 2.0))
        reg.observe('kyverno_admission_review_duration_seconds', 0.3)
        assert reg.histogram_count(
            'kyverno_admission_review_duration_seconds') == 2


class TestEvents:
    def test_violation_events_created(self):
        client = FakeClient()
        gen = EventGenerator(client)
        gen.run()
        try:
            events = events_for_response(run_engine({}))
            assert len(events) == 1
            assert events[0]['reason'] == 'PolicyViolation'
            gen.add(*events)
            gen.drain()
            stored = client.list_resource('v1', 'Event', 'd', None)
            assert len(stored) == 1
            assert 'm/r fail' in stored[0]['message']
        finally:
            gen.stop()

    def test_queue_bound(self):
        client = FakeClient()
        gen = EventGenerator(client, max_queued=2)
        events = events_for_response(run_engine({}))
        for _ in range(5):
            gen.add(*events)
        assert gen.dropped == 3


class TestLogging:
    def test_json_format(self, capsys):
        from kyverno_tpu.observability.logging import (FORMAT_JSON, setup,
                                                       with_values)
        logger = setup(FORMAT_JSON, logging.INFO)
        with_values(logger, 'applied policy', policy='m', rules=2)
        err = capsys.readouterr().err.strip()
        doc = json.loads(err.splitlines()[-1])
        assert doc['msg'] == 'applied policy'
        assert doc['policy'] == 'm' and doc['rules'] == 2
