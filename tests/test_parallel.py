"""Multi-device sharded evaluation on the virtual CPU mesh
(conftest forces 8 host devices; VERDICT r1 item 7).

Exercises mesh.distributed_scan_step from pytest: uneven shard sizes,
batches alongside host-fallback policies, and the summary==histogram
invariant that the psum reduction must satisfy."""

import numpy as np
import pytest
import yaml

import jax

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.ir import N_STATUS_CODES
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.parallel.mesh import (distributed_scan_step, make_mesh,
                                       pad_to_multiple)

PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: mesh-pack
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: no-latest
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: no latest
        pattern:
          spec:
            containers:
              - image: "!*:latest"
    - name: deny-default
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: not in default
        deny:
          conditions:
            any:
              - key: "{{request.object.metadata.namespace}}"
                operator: Equals
                value: default
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: host-only
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: needs-context
      match: {any: [{resources: {kinds: [Pod]}}]}
      context:
        - name: cm
          configMap: {name: x, namespace: y}
      validate:
        message: m
        deny: {conditions: {any: [{key: "{{cm.data.v}}", operator: Equals, value: x}]}}
"""


def pods(n):
    return [{'apiVersion': 'v1', 'kind': 'Pod',
             'metadata': {'name': f'p{i}',
                          'namespace': 'default' if i % 3 else 'kube'},
             'spec': {'containers': [
                 {'name': 'c',
                  'image': 'nginx:latest' if i % 2 else 'nginx:1.25'}]}}
            for i in range(n)]


@pytest.fixture(scope='module')
def mesh():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip('needs the 8-device virtual mesh')
    return make_mesh(devices[:8])


class TestDistributedScan:
    def test_summary_matches_histogram(self, mesh):
        policies = [Policy(d) for d in yaml.safe_load_all(PACK)]
        cps = compile_policies(policies)
        assert cps.host_rules  # host-fallback policy present in the set
        resources = pods(24)
        statuses, summary = distributed_scan_step(cps, mesh, resources)
        assert statuses.shape == (24, len(cps.programs))
        assert summary.shape == (len(cps.programs), N_STATUS_CODES)
        expect = np.zeros_like(summary)
        for j in range(statuses.shape[1]):
            for s in range(N_STATUS_CODES):
                expect[j, s] = int((statuses[:, j] == s).sum())
        assert (summary == expect).all()

    @pytest.mark.parametrize('n', [1, 7, 8, 9, 23])
    def test_uneven_batches(self, mesh, n):
        policies = [Policy(d) for d in yaml.safe_load_all(PACK)]
        cps = compile_policies(policies)
        statuses, summary = distributed_scan_step(cps, mesh, pods(n))
        assert statuses.shape[0] == n
        # padded rows must not pollute the summary
        assert int(summary.sum()) == n * len(cps.programs)

    def test_matches_single_device_scan(self, mesh):
        policies = [Policy(d) for d in yaml.safe_load_all(PACK)]
        resources = pods(13)
        cps = compile_policies(policies)
        statuses, _ = distributed_scan_step(cps, mesh, resources)
        scanner = BatchScanner(policies)
        single, _, _ = scanner.scan_statuses(resources)
        assert (statuses == single).all()

    def test_pad_to_multiple(self):
        assert pad_to_multiple(13, 8) == 16
        assert pad_to_multiple(16, 8) == 16
        assert pad_to_multiple(1, 8) == 8
