"""Fleet observatory unit surface (ISSUE 18): federation merge rules,
the registry series-cardinality guard, skew-analyzer rate limiting,
the persistent-cache feature guard, and the straggler advisor branch.

The mesh-integration half (bit-identity under KTPU_FLEET=0, injected
straggler blame, endpoint/CLI agreement) lives in
tests/test_distributed.py against the conftest 8-device mesh.
"""

import os

import pytest

from kyverno_tpu.observability import fleet, timeline
from kyverno_tpu.observability.metrics import (SERIES_DROPPED,
                                               MetricsRegistry,
                                               global_registry,
                                               set_global_registry)


# -- series-cardinality guard (KTPU_METRIC_SERIES_MAX) ------------------------

class TestSeriesCardinalityGuard:
    def test_new_series_beyond_cap_refused_and_counted(self, monkeypatch):
        monkeypatch.setenv('KTPU_METRIC_SERIES_MAX', '3')
        reg = MetricsRegistry()
        for i in range(5):
            reg.inc('kyverno_tpu_test_total', path=str(i))
        snap = reg.snapshot()
        assert len(snap['counters']['kyverno_tpu_test_total']) == 3
        assert reg.counter_value(
            SERIES_DROPPED, metric='kyverno_tpu_test_total') == 2.0
        # existing series keep updating after the cap is hit
        reg.inc('kyverno_tpu_test_total', path='0')
        assert reg.counter_value('kyverno_tpu_test_total', path='0') == 2.0
        # no further drops for the update
        assert reg.counter_value(
            SERIES_DROPPED, metric='kyverno_tpu_test_total') == 2.0

    def test_guard_covers_gauges_and_histograms(self, monkeypatch):
        monkeypatch.setenv('KTPU_METRIC_SERIES_MAX', '2')
        reg = MetricsRegistry()
        for i in range(4):
            reg.set_gauge('kyverno_tpu_test_ratio', 1.0, shard=str(i))
            reg.observe('kyverno_tpu_test_seconds', 0.1, shard=str(i))
        snap = reg.snapshot()
        assert len(snap['gauges']['kyverno_tpu_test_ratio']) == 2
        assert len(snap['hists']['kyverno_tpu_test_seconds']['series']) == 2
        assert reg.counter_value(
            SERIES_DROPPED, metric='kyverno_tpu_test_ratio') == 2.0
        assert reg.counter_value(
            SERIES_DROPPED, metric='kyverno_tpu_test_seconds') == 2.0

    def test_drop_counter_bypasses_its_own_cap(self, monkeypatch):
        monkeypatch.setenv('KTPU_METRIC_SERIES_MAX', '1')
        reg = MetricsRegistry()
        # overflow three different metrics: the drop counter needs one
        # series per overflowed metric, beyond its own cap of 1
        for name in ('kyverno_tpu_a_total', 'kyverno_tpu_b_total',
                     'kyverno_tpu_c_total'):
            reg.inc(name, k='0')
            reg.inc(name, k='1')
        assert len(reg.snapshot()['counters'][SERIES_DROPPED]) == 3


# -- federation merge rules ---------------------------------------------------

def _snap(ident, counters=(), gauges=(), residency=(), hists=()):
    reg = MetricsRegistry()
    for name, value, labels in counters:
        reg.inc(name, value, **labels)
    for name, value, labels in gauges:
        reg.set_gauge(name, value, **labels)
    for name in residency:
        reg.mark_reset_on_close(name)
    for name, buckets, samples in hists:
        reg.register_histogram(name, buckets)
        for value, labels in samples:
            reg.observe(name, value, **labels)
    return reg.snapshot(ident)


class TestFederationMerge:
    def test_counters_sum_gauges_follow_residency(self):
        a = _snap({'host': 'a', 'pid': 1, 'process_index': 0},
                  counters=[('c_total', 2.0, {'path': 'x'})],
                  gauges=[('queue_depth', 3.0, {}), ('ratio', 0.5, {})],
                  residency=['queue_depth'])
        b = _snap({'host': 'b', 'pid': 2, 'process_index': 1},
                  counters=[('c_total', 5.0, {'path': 'x'})],
                  gauges=[('queue_depth', 4.0, {}), ('ratio', 0.9, {})],
                  residency=['queue_depth'])
        merged = fleet.FleetRegistry.merge([a, b])
        totals = fleet.FleetRegistry.counter_totals(merged)
        assert totals['c_total'] == 7.0
        gauges = {name: sum(v for _k, v in entries)
                  for name, entries in merged['gauges'].items()}
        # residency gauge: fleet occupancy is the sum of per-host
        # occupancy; state gauge: max (an average describes no process)
        assert gauges['queue_depth'] == 7.0
        assert gauges['ratio'] == 0.9
        assert merged['reset_on_close'] == ['queue_depth']
        assert len(merged['identities']) == 2

    def test_histograms_merge_bucketwise(self):
        buckets = (0.1, 1.0)
        a = _snap({'host': 'a', 'pid': 1, 'process_index': 0},
                  hists=[('h_seconds', buckets,
                          [(0.05, {'shard': '0'}), (0.5, {'shard': '0'})])])
        b = _snap({'host': 'b', 'pid': 2, 'process_index': 1},
                  hists=[('h_seconds', buckets,
                          [(0.05, {'shard': '0'})])])
        merged = fleet.FleetRegistry.merge([a, b])
        h = merged['hists']['h_seconds']
        assert h['bucket_conflict'] is False
        [entry] = h['series']
        assert entry[1] == 3          # count
        assert entry[2] == pytest.approx(0.6)
        assert entry[3] == [2, 3]     # cumulative bucket counts summed

    def test_bucket_conflict_flagged_not_fabricated(self):
        a = _snap({'host': 'a', 'pid': 1, 'process_index': 0},
                  hists=[('h_seconds', (0.1, 1.0), [(0.5, {})])])
        b = _snap({'host': 'b', 'pid': 2, 'process_index': 1},
                  hists=[('h_seconds', (0.2, 2.0, 5.0), [(0.5, {})])])
        merged = fleet.FleetRegistry.merge([a, b])
        h = merged['hists']['h_seconds']
        assert h['bucket_conflict'] is True
        # count/sum still compose even when buckets cannot
        [entry] = h['series']
        assert entry[1] == 2 and entry[2] == pytest.approx(1.0)

    def test_merge_is_associative_over_merged_docs(self):
        docs = [
            _snap({'host': h, 'pid': p, 'process_index': i},
                  counters=[('c_total', v, {})],
                  gauges=[('g', g, {})],
                  hists=[('h_seconds', (0.1, 1.0), [(v / 10.0, {})])])
            for h, p, i, v, g in (('a', 1, 0, 1.0, 0.2),
                                  ('b', 2, 1, 2.0, 0.4),
                                  ('c', 3, 2, 4.0, 0.8))]
        flat = fleet.FleetRegistry.merge(docs)
        nested = fleet.FleetRegistry.merge(
            [fleet.FleetRegistry.merge(docs[:2]), docs[2]])
        assert nested == flat

    def test_add_snapshot_is_idempotent_per_identity(self):
        fr = fleet.FleetRegistry()
        doc = _snap({'host': 'a', 'pid': 1, 'process_index': 0},
                    counters=[('c_total', 3.0, {})])
        fr.add_snapshot(doc)
        fr.add_snapshot(dict(doc))  # re-announce: replaces, not doubles
        merged = fr.merged()
        assert fleet.FleetRegistry.counter_totals(merged) == \
            {'c_total': 3.0}
        assert len(merged['identities']) == 1

    def test_snapshot_file_roundtrip(self, tmp_path):
        reg = MetricsRegistry()
        reg.inc('c_total', 2.0, mesh='data8')
        path = str(tmp_path / 'host.jsonl')
        fleet.write_snapshot(path, reg)
        fleet.write_snapshot(path, reg)  # JSONL appends
        docs = fleet.read_snapshot_files([path])
        assert len(docs) == 2
        assert all(fleet.FleetRegistry.counter_totals(d) ==
                   {'c_total': 2.0} for d in docs)


# -- skew analyzer ------------------------------------------------------------

class TestSkewAnalyzer:
    DEVICES = [f'dev{i}' for i in range(4)]

    def test_balanced_walls_never_sustain(self):
        an = fleet.SkewAnalyzer(window=2,
                                profile_trigger=lambda: None)
        for _ in range(4):
            v = an.fold('data4', [0.1, 0.1, 0.1, 0.1], self.DEVICES)
        assert v['skew'] == 1.0
        assert v['sustained'] is False
        assert 'bound_by' not in v

    def test_sustained_fire_is_rate_limited(self):
        clock = [0.0]
        fired = []
        an = fleet.SkewAnalyzer(window=2, now=lambda: clock[0],
                                profile_trigger=lambda: fired.append(1))
        skewed = [0.9, 0.1, 0.1, 0.1]
        balanced = [0.1, 0.1, 0.1, 0.1]
        for _ in range(2):
            v = an.fold('data4', skewed, self.DEVICES)
        assert v['sustained'] and v['slow_shard'] == 0
        assert v['device'] == 'dev0'
        # the capture thread is synchronous enough to join via verdict
        import time
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fired == [1]
        # drop to balanced (sustained falls), re-skew inside the
        # rate-limit interval: no second capture
        for _ in range(2):
            an.fold('data4', balanced, self.DEVICES)
        for _ in range(2):
            an.fold('data4', skewed, self.DEVICES)
        assert fired == [1]
        # past the interval the next False->True transition fires again
        clock[0] = fleet.PROFILE_MIN_INTERVAL_S + 1.0
        for _ in range(2):
            an.fold('data4', balanced, self.DEVICES)
        for _ in range(2):
            an.fold('data4', skewed, self.DEVICES)
        deadline = time.monotonic() + 5.0
        while len(fired) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fired == [1, 1]
        assert an.auto_profiles == 2

    def test_windows_are_per_mesh_shape(self):
        an = fleet.SkewAnalyzer(window=2, profile_trigger=lambda: None)
        an.fold('data4', [0.9, 0.1, 0.1, 0.1], self.DEVICES)
        # one skewed step on another mesh must not inherit data4's
        # window history
        v = an.fold('data8', [0.9] + [0.1] * 7,
                    [f'dev{i}' for i in range(8)])
        assert v['sustained'] is False

    def test_window_knob_floor(self, monkeypatch):
        monkeypatch.setenv('KTPU_FLEET_SKEW_WINDOW', '0')
        an = fleet.SkewAnalyzer(profile_trigger=lambda: None)
        assert an.window == 2
        monkeypatch.setenv('KTPU_FLEET_SKEW_WINDOW', 'junk')
        assert fleet.SkewAnalyzer(profile_trigger=lambda: None).window == 16


# -- persistent-cache feature guard -------------------------------------------

class TestCacheFeatureGuard:
    def test_mismatched_hostkey_rejects_and_rescopes(self, tmp_path):
        from kyverno_tpu.aotcache import keys
        prev = global_registry()
        reg = MetricsRegistry()
        set_global_registry(reg)
        try:
            cache_dir = str(tmp_path / 'xla')
            os.makedirs(cache_dir)
            fp = keys.host_fingerprint()
            # fresh dir: marker written, dir accepted as-is
            used, rejected = keys.verify_cache_feature_scope(cache_dir)
            assert (used, rejected) == (cache_dir, False)
            marker = os.path.join(cache_dir, keys.HOSTKEY_FILE)
            assert open(marker).read().strip() == fp
            # matching marker: accepted again, nothing counted
            assert keys.verify_cache_feature_scope(cache_dir) == \
                (cache_dir, False)
            assert reg.counter_total(keys.AOT_LOAD_REJECTED) == 0.0
            # a dir populated by a different CPU feature set: rejected,
            # counted, and re-scoped to a feat-<digest> subdir with its
            # own matching marker
            with open(marker, 'w') as f:
                f.write('feedface00')
            used3, rejected3 = keys.verify_cache_feature_scope(cache_dir)
            assert rejected3 is True
            assert used3 == os.path.join(cache_dir, f'feat-{fp}')
            assert reg.counter_value(
                keys.AOT_LOAD_REJECTED,
                reason='feature_mismatch') == 1.0
            assert open(os.path.join(
                used3, keys.HOSTKEY_FILE)).read().strip() == fp
            # the re-scoped dir now verifies clean
            assert keys.verify_cache_feature_scope(used3) == \
                (used3, False)
        finally:
            set_global_registry(prev)


# -- straggler advisor branch -------------------------------------------------

class TestStragglerAdvice:
    def test_straggler_branch_names_the_shard(self):
        suggest, note = timeline.advise('straggler', 0.7,
                                        detail='shard 3 (TPU_3)')
        assert suggest == {}  # no host-pipeline knob fixes a slow chip
        assert 'shard 3 (TPU_3)' in note
        assert '70%' in note

    def test_existing_two_arg_callers_unchanged(self):
        suggest, note = timeline.advise('device_eval', 0.5)
        assert isinstance(suggest, dict) and isinstance(note, str)
        assert 'straggler' not in note
