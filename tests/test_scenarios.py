"""Replay of the reference YAML scenario corpus (test/scenarios) through
the engine (reference: pkg/testrunner/scenario.go:30-50 +
testrunner_test.go's enabled list), consumed in place from the read-only
reference checkout."""

import os

import pytest

from kyverno_tpu.conformance.scenarios import REF_ROOT, run_scenario

#: the reference's own enabled scenario list
#: (pkg/testrunner/testrunner_test.go)
SCENARIOS = [
    'test/scenarios/other/scenario_mutate_endpoint.yaml',
    'test/scenarios/other/scenario_mutate_validate_qos.yaml',
    'test/scenarios/samples/best_practices/disallow_priviledged.yaml',
    'test/scenarios/other/scenario_validate_healthChecks.yaml',
    'test/scenarios/samples/best_practices/disallow_host_network_port.yaml',
    'test/scenarios/samples/best_practices/disallow_host_pid_ipc.yaml',
    'test/scenarios/other/'
    'scenario_validate_disallow_default_serviceaccount.yaml',
    'test/scenarios/other/scenario_validate_selinux_context.yaml',
    'test/scenarios/other/scenario_validate_default_proc_mount.yaml',
    'test/scenarios/other/scenario_validate_volume_whiltelist.yaml',
    'test/scenarios/samples/best_practices/disallow_bind_mounts_fail.yaml',
    'test/scenarios/samples/best_practices/disallow_bind_mounts_pass.yaml',
    'test/scenarios/samples/best_practices/add_safe_to_evict.yaml',
    'test/scenarios/samples/best_practices/add_safe_to_evict2.yaml',
    'test/scenarios/samples/best_practices/add_safe_to_evict3.yaml',
    'test/scenarios/samples/more/restrict_automount_sa_token.yaml',
    'test/scenarios/samples/more/restrict_ingress_classes.yaml',
    'test/scenarios/samples/more/unknown_ingress_class.yaml',
    # additional corpus files beyond the reference's enabled list
    'test/scenarios/other/scenario_mutate_pod_spec.yaml',
    'test/scenarios/samples/best_practices/add_networkPolicy.yaml',
    'test/scenarios/samples/best_practices/add_ns_quota.yaml',
]


def _exists(rel):
    return os.path.isfile(os.path.join(REF_ROOT, rel))


def test_scenario_paths_exist():
    missing = [s for s in SCENARIOS if not _exists(s)]
    assert not missing, f'scenario corpus drifted: {missing}'


@pytest.mark.parametrize('rel', SCENARIOS)
def test_scenario(rel):
    assert run_scenario(rel) >= 1
