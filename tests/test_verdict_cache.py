"""Digest-keyed verdict cache (ISSUE 6): spec-digest stability, store
hit/miss/invalidation/eviction semantics, controller integration
(replay vs scan partition, delete invalidation, policy-set flush), the
KTPU_VERDICT_CACHE=off bit-identity oracle, and second-process
disk-store reuse."""

import os
import sys

import pytest
import yaml

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from kyverno_tpu.api.policy import Policy  # noqa: E402
from kyverno_tpu.dclient.client import FakeClient  # noqa: E402
from kyverno_tpu.observability.metrics import (MetricsRegistry,  # noqa: E402
                                               set_global_registry)
from kyverno_tpu.reports.controllers import (  # noqa: E402
    BackgroundScanController, MetadataCache)
from kyverno_tpu.verdictcache import (VerdictCache, engine_rev,  # noqa: E402
                                      generation_key, spec_digest)

POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: audit
  rules:
    - name: team-label
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: team label required
        pattern:
          metadata:
            labels:
              team: "?*"
""")

OTHER_POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-owner
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: audit
  rules:
    - name: owner-label
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: owner label required
        pattern:
          metadata:
            labels:
              owner: "?*"
""")

NOW = 1754000000.0


def pod(name, team=None, uid=None):
    labels = {'team': team} if team else {}
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'uid': uid or f'uid-{name}', 'labels': labels},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


@pytest.fixture(autouse=True)
def _registry():
    reg = MetricsRegistry()
    set_global_registry(reg)
    yield reg
    set_global_registry(None)


def make_ctrl(tmp_path, monkeypatch, enabled=True, policies=None,
              client=None):
    monkeypatch.setenv('KTPU_VERDICT_CACHE', '1' if enabled else '0')
    monkeypatch.setenv('KTPU_VERDICT_CACHE_DIR', str(tmp_path / 'vc'))
    return BackgroundScanController(
        client or FakeClient(),
        [Policy(p) for p in (policies or [POLICY])], cache=MetadataCache())


def reports_of(ctrl):
    """Stored reports with the fake API server's own write bookkeeping
    (metadata.resourceVersion bumps per update, server-assigned
    metadata.uid) normalized away — the bit-identity contract is about
    report *content*."""
    out = []
    for r in sorted(ctrl.client.list_resource(
            'kyverno.io/v1alpha2', 'BackgroundScanReport', 'default',
            None), key=lambda r: r['metadata']['name']):
        r = dict(r, metadata={k: v for k, v in r['metadata'].items()
                              if k not in ('resourceVersion', 'uid')})
        out.append(r)
    return out


# ---------------------------------------------------------------------------
# spec digest


class TestSpecDigest:
    def test_key_order_and_volatile_metadata_irrelevant(self):
        a = pod('p', team='infra')
        # same content, different key order + server-side bookkeeping
        b = {
            'kind': 'Pod', 'apiVersion': 'v1',
            'spec': {'containers': [{'image': 'nginx', 'name': 'c'}]},
            'metadata': {
                'labels': {'team': 'infra'}, 'uid': 'uid-p',
                'namespace': 'default', 'name': 'p',
                'resourceVersion': '123456',
                'generation': 7,
                'creationTimestamp': '2026-01-01T00:00:00Z',
                'managedFields': [{'manager': 'kubectl',
                                   'operation': 'Apply'}],
            },
        }
        assert spec_digest(a) == spec_digest(b)

    def test_changed_content_misses(self):
        base = pod('p', team='infra')
        changed = pod('p', team='other')
        assert spec_digest(base) != spec_digest(changed)
        with_status = pod('p', team='infra')
        with_status['status'] = {'phase': 'Running'}
        assert spec_digest(base) != spec_digest(with_status)

    def test_recreated_uid_misses(self):
        # a deleted-then-recreated resource gets a fresh uid, so even
        # identical content never aliases the predecessor's entries
        assert spec_digest(pod('p', uid='u1')) != \
            spec_digest(pod('p', uid='u2'))

    def test_digest_does_not_mutate_the_resource(self):
        p = pod('p')
        p['metadata']['resourceVersion'] = '42'
        spec_digest(p)
        assert p['metadata']['resourceVersion'] == '42'


# ---------------------------------------------------------------------------
# store


ROW = ([{'source': 'kyverno', 'policy': 'require-team',
         'rule': 'team-label', 'message': 'ok', 'result': 'pass',
         'scored': True, 'timestamp': {'seconds': 1}}],
       {'pass': 1, 'fail': 0, 'warn': 0, 'error': 0, 'skip': 0}, [0])


class TestStore:
    def test_hit_miss_and_replay_stamps_timestamp(self, tmp_path,
                                                  _registry):
        vc = VerdictCache('fp', root=str(tmp_path))
        assert vc.lookup('d1') is None
        results, summary, idx = ROW
        vc.store('d1', 'u1', results, summary, idx)
        row = vc.lookup('d1')
        assert row is not None
        policies = [Policy(POLICY)]
        r2, s2, p2 = vc.replay(row, policies, ts=99)
        assert r2[0]['timestamp'] == {'seconds': 99}
        assert {k: v for k, v in r2[0].items() if k != 'timestamp'} == \
            {k: v for k, v in results[0].items() if k != 'timestamp'}
        assert s2 == summary and p2 == policies
        assert _registry.counter_value(
            'kyverno_tpu_verdict_cache_hits_total') == 1.0
        assert _registry.counter_value(
            'kyverno_tpu_verdict_cache_misses_total') == 1.0

    def test_uid_invalidation_drops_entries(self, tmp_path):
        vc = VerdictCache('fp', root=str(tmp_path))
        vc.store('d1', 'u1', *ROW)
        vc.store('d2', 'u1', *ROW)
        vc.store('d3', 'u2', *ROW)
        assert vc.invalidate_uid('u1') == 2
        assert vc.lookup('d1') is None and vc.lookup('d2') is None
        assert vc.lookup('d3') is not None

    def test_memory_lru_eviction_counts(self, tmp_path, _registry):
        vc = VerdictCache('fp', root=str(tmp_path), max_entries=2)
        vc.store('d1', 'u1', *ROW)
        vc.store('d2', 'u2', *ROW)
        vc.lookup('d1')  # refresh: d2 becomes LRU
        vc.store('d3', 'u3', *ROW)
        assert vc.lookup('d2') is None and vc.lookup('d1') is not None
        assert _registry.counter_value(
            'kyverno_tpu_verdict_cache_evictions_total') == 1.0

    def test_snapshot_roundtrip_and_corruption(self, tmp_path):
        vc = VerdictCache('fp', root=str(tmp_path))
        vc.store('d1', 'u1', *ROW)
        assert vc.flush()
        assert not vc.flush()  # clean: nothing to write
        again = VerdictCache('fp', root=str(tmp_path))
        assert again.lookup('d1') is not None
        assert again.invalidate_uid('u1') == 1  # uid index rebuilt
        # a bit-flipped snapshot is dropped and loaded as empty
        path = vc.path()
        raw = bytearray(open(path, 'rb').read())
        raw[-1] ^= 0xFF
        open(path, 'wb').write(bytes(raw))
        fresh = VerdictCache('fp', root=str(tmp_path))
        assert len(fresh) == 0
        assert not os.path.exists(path)

    def test_generation_isolation_and_disk_eviction(self, tmp_path):
        old = VerdictCache('fp-old', root=str(tmp_path), max_bytes=1)
        old.store('d1', 'u1', *ROW)
        old.flush()
        # different fingerprint = different generation: no aliasing
        new = VerdictCache('fp-new', root=str(tmp_path), max_bytes=1)
        assert new.lookup('d1') is None
        os.utime(old.path(), (1, 1))  # age the old generation
        new.store('d1', 'u1', *ROW)
        new.flush()  # budget of 1 byte: the old generation is evicted
        assert not os.path.exists(old.path())
        assert os.path.exists(new.path())

    def test_engine_rev_scopes_generation(self, tmp_path, monkeypatch):
        a = VerdictCache('fp', root=str(tmp_path), rev='rev-a')
        a.store('d1', 'u1', *ROW)
        a.flush()
        b = VerdictCache('fp', root=str(tmp_path), rev='rev-b')
        assert b.lookup('d1') is None  # code change never replays
        assert generation_key('fp', 'rev-a') != generation_key(
            'fp', 'rev-b')
        assert engine_rev()  # derivable in this tree


# ---------------------------------------------------------------------------
# controller integration


def seed(ctrl, pods):
    for p in pods:
        ctrl.enqueue(p)


class TestControllerIntegration:
    def test_warm_rescan_replays_without_scanning(self, tmp_path,
                                                  monkeypatch):
        ctrl = make_ctrl(tmp_path, monkeypatch)
        pods = [pod('good', team='infra'), pod('bad')]
        seed(ctrl, pods)
        assert len(ctrl.reconcile(now=NOW)) == 2
        assert ctrl.rescan_stats == {
            'rows_pending': 2, 'rows_scanned': 2, 'rows_replayed': 0}
        first = reports_of(ctrl)
        # a full report-rebuild demand (restart semantics) replays from
        # the cache — the device scanner must not run at all
        monkeypatch.setattr(
            ctrl.scanner, 'scan_report_results',
            lambda *a, **k: pytest.fail('warm rescan must not scan'))
        ctrl.reset_scan_state()
        ctrl.enqueue_all()
        assert len(ctrl.reconcile(now=NOW)) == 2
        assert ctrl.rescan_stats == {
            'rows_pending': 2, 'rows_scanned': 0, 'rows_replayed': 2}
        assert reports_of(ctrl) == first

    def test_churn_scans_only_changed_rows(self, tmp_path, monkeypatch,
                                           _registry):
        ctrl = make_ctrl(tmp_path, monkeypatch)
        pods = [pod(f'p{i}', team='infra') for i in range(8)]
        seed(ctrl, pods)
        ctrl.reconcile(now=NOW)
        pods[3]['metadata']['labels'] = {}  # churn one row
        ctrl.cache.update(pods[3])
        ctrl.reset_scan_state()
        ctrl.enqueue_all()
        ctrl.reconcile(now=NOW + 30)
        assert ctrl.rescan_stats == {
            'rows_pending': 8, 'rows_scanned': 1, 'rows_replayed': 7}
        assert _registry.gauge_value(
            'kyverno_tpu_rescan_rows_scanned') == 1.0
        assert _registry.gauge_value(
            'kyverno_tpu_rescan_rows_replayed') == 7.0
        # the churned row's report reflects the new content
        failed = [r for r in reports_of(ctrl)
                  if r['metadata']['ownerReferences'][0]['name'] == 'p3']
        assert failed[0]['spec']['summary']['fail'] == 1

    def test_delete_drops_verdict_entries(self, tmp_path, monkeypatch):
        ctrl = make_ctrl(tmp_path, monkeypatch)
        p = pod('gone', team='infra')
        seed(ctrl, [p])
        ctrl.reconcile(now=NOW)
        assert len(ctrl.verdict_cache) == 1
        ctrl.cache.remove(p)
        assert len(ctrl.verdict_cache) == 0

    def test_policy_change_opens_new_generation(self, tmp_path,
                                                monkeypatch):
        ctrl = make_ctrl(tmp_path, monkeypatch)
        seed(ctrl, [pod('p', team='infra')])
        ctrl.reconcile(now=NOW)
        gen_before = ctrl.verdict_cache.fingerprint
        ctrl.set_policies([Policy(OTHER_POLICY)])
        assert ctrl.verdict_cache.fingerprint != gen_before
        ctrl.enqueue(pod('p', team='infra'))
        ctrl.reconcile(now=NOW + 60)
        assert ctrl.rescan_stats['rows_scanned'] == 1
        assert ctrl.rescan_stats['rows_replayed'] == 0

    def test_off_switch_bit_identical_reports(self, tmp_path,
                                              monkeypatch):
        """ISSUE 6 acceptance: cached-rescan output is pinned against a
        fresh dense scan — KTPU_VERDICT_CACHE=off produces bit-identical
        BackgroundScanReports for the same (resources, policies, now)."""
        pods = [pod('good', team='infra'), pod('bad'), pod('mid')]
        cached = make_ctrl(tmp_path, monkeypatch, enabled=True)
        seed(cached, [pod('good', team='infra'), pod('bad'), pod('mid')])
        cached.reconcile(now=NOW)       # populate the cache
        cached.reset_scan_state()
        seed(cached, pods)
        cached.reconcile(now=NOW + 30)  # replayed pass
        assert cached.rescan_stats['rows_replayed'] == 3
        dense = make_ctrl(tmp_path / 'dense', monkeypatch, enabled=False)
        assert dense.verdict_cache is None
        seed(dense, [pod('good', team='infra'), pod('bad'), pod('mid')])
        dense.reconcile(now=NOW + 30)
        assert dense.rescan_stats['rows_replayed'] == 0
        assert reports_of(cached) == reports_of(dense)

    def test_second_process_disk_store_reuse(self, tmp_path,
                                             monkeypatch):
        """A fresh controller (new process: cold memory, same cache dir
        and policy set) replays from the persisted snapshot with zero
        device scans."""
        first = make_ctrl(tmp_path, monkeypatch)
        pods = [pod('a', team='x'), pod('b')]
        seed(first, pods)
        first.reconcile(now=NOW)
        first.close()  # daemon-shutdown flush
        second = make_ctrl(tmp_path, monkeypatch)
        monkeypatch.setattr(
            second.scanner, 'scan_report_results',
            lambda *a, **k: pytest.fail('disk-warm rescan must not scan'))
        seed(second, [pod('a', team='x'), pod('b')])
        assert len(second.reconcile(now=NOW)) == 2
        assert second.rescan_stats == {
            'rows_pending': 2, 'rows_scanned': 0, 'rows_replayed': 2}
        assert reports_of(second) == reports_of(first)
