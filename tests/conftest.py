import os
import sys

# Force a virtual 8-device CPU mesh for all sharding tests; must be set before
# jax is imported anywhere in the test session. Override unconditionally —
# the ambient environment may point JAX_PLATFORMS at a real TPU.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
