import os
import sys

# Force a virtual 8-device CPU mesh for all sharding tests; must happen
# before any jax backend initialization. The ambient environment registers a
# real-TPU PJRT plugin via sitecustomize and pins JAX_PLATFORMS, so the env
# var alone is not enough — override the jax config directly.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
