"""The fused report path (BatchScanner.scan_report_results +
set_fused_results) must be bit-identical to the unfused path
(scan_stream → set_responses) — it only skips the intermediate
EngineResponse objects, never changes report content."""

import random

import pytest

import bench
from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.reports.results import set_fused_results, set_responses
from kyverno_tpu.reports.types import new_background_scan_report

PACK = bench.PACK + """
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: psp-restricted
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: restricted
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        podSecurity:
          level: baseline
          version: latest
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: no-background
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  background: false
  rules:
    - name: never-in-scan
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "x"
        pattern:
          metadata:
            name: "?*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: one-rule-mode
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  applyRules: One
  rules:
    - name: first
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "needs app label"
        pattern:
          metadata:
            labels:
              app: "?*"
"""


def _strip_ts(results):
    return [{k: v for k, v in r.items() if k != 'timestamp'}
            for r in results]


@pytest.fixture(scope='module')
def scanner():
    return BatchScanner(load_policies_from_yaml(PACK))


def test_fused_matches_unfused(scanner):
    rng = random.Random(3)
    pods = [bench.make_pod(rng, i) for i in range(96)]

    unfused = []
    for pod, responses in zip(pods, scanner.scan_stream(pods)):
        report = new_background_scan_report(pod)
        relevant = [r for r in responses if r.policy_response.rules]
        set_responses(report, *relevant)
        unfused.append(report)

    fused = []
    for pod, (results, summary, policies) in zip(
            pods, scanner.scan_report_results(pods)):
        report = new_background_scan_report(pod)
        set_fused_results(report, results, summary, policies)
        fused.append(report)

    assert len(fused) == len(unfused)
    for f, u in zip(fused, unfused):
        assert f['metadata'].get('labels') == u['metadata'].get('labels')
        fs, us = f['spec'], u['spec']
        assert fs['summary'] == us['summary']
        assert _strip_ts(fs['results']) == _strip_ts(us['results'])


def test_fused_results_are_sorted(scanner):
    rng = random.Random(5)
    pods = [bench.make_pod(rng, i) for i in range(8)]
    for results, _summary, _p in scanner.scan_report_results(pods):
        keys = [(r.get('policy', ''), r.get('rule', '')) for r in results]
        assert keys == sorted(keys)
