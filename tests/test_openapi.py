"""OpenAPI schema validation of mutated resources
(reference: pkg/openapi/manager.go)."""

import json

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.openapi.manager import Manager, ValidationError


class TestValidateResource:
    def test_accepts_valid_pod(self):
        Manager().validate_resource({
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'labels': {'a': 'b'}},
            'spec': {'containers': [{'name': 'c'}]}})

    def test_rejects_bad_types(self):
        m = Manager()
        with pytest.raises(ValidationError, match='labels'):
            m.validate_resource({
                'kind': 'Pod', 'metadata': {'labels': 'not-a-map'},
                'spec': {}})
        with pytest.raises(ValidationError, match='replicas'):
            m.validate_resource({
                'kind': 'Deployment', 'metadata': {'name': 'd'},
                'spec': {'replicas': 'three'}})
        with pytest.raises(ValidationError, match='containers'):
            m.validate_resource({
                'kind': 'Pod', 'metadata': {'name': 'p'},
                'spec': {'containers': {'name': 'not-a-list'}}})

    def test_unknown_kind_tolerated(self):
        Manager().validate_resource({'kind': 'MyCRD',
                                     'spec': 'anything-goes'})

    def test_add_schema(self):
        m = Manager()
        m.add_schema('MyCRD', {'spec.size': 'integer'})
        with pytest.raises(ValidationError):
            m.validate_resource({'kind': 'MyCRD',
                                 'spec': {'size': 'big'}})


class TestPolicyMutationDryRun:
    def test_valid_mutation_passes(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: ok, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: add-label
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchStrategicMerge:
          metadata:
            labels:
              +(x): "y"
"""))
        Manager().validate_policy_mutation(policy)

    def test_type_breaking_mutation_rejected(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: bad, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: break-labels
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchesJson6902: |-
          - op: add
            path: /metadata/labels
            value: "oops"
"""))
        with pytest.raises(ValidationError):
            Manager().validate_policy_mutation(policy)


class TestMutationWebhookIntegration:
    def test_schema_breaking_patch_denied(self):
        from tests.test_webhooks import make_cache, pod, review, serve
        bad_mutate = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: break-replicas
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: bad
      match: {any: [{resources: {kinds: [Deployment]}}]}
      mutate:
        patchesJson6902: |-
          - op: add
            path: /spec/replicas
            value: "three"
"""
        server = serve(make_cache(bad_mutate))
        deploy = {'apiVersion': 'apps/v1', 'kind': 'Deployment',
                  'metadata': {'name': 'd', 'namespace': 'default'},
                  'spec': {'replicas': 1}}
        body = server.handle('/mutate', json.dumps(review(deploy)).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        assert 'schema validation' in resp['status']['message']
