"""OpenAPI schema validation of mutated resources
(reference: pkg/openapi/manager.go)."""

import json

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.openapi.manager import Manager, ValidationError


class TestValidateResource:
    def test_accepts_valid_pod(self):
        Manager().validate_resource({
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'labels': {'a': 'b'}},
            'spec': {'containers': [{'name': 'c'}]}})

    def test_rejects_bad_types(self):
        m = Manager()
        with pytest.raises(ValidationError, match='labels'):
            m.validate_resource({
                'kind': 'Pod', 'metadata': {'labels': 'not-a-map'},
                'spec': {}})
        with pytest.raises(ValidationError, match='replicas'):
            m.validate_resource({
                'kind': 'Deployment', 'metadata': {'name': 'd'},
                'spec': {'replicas': 'three'}})
        with pytest.raises(ValidationError, match='containers'):
            m.validate_resource({
                'kind': 'Pod', 'metadata': {'name': 'p'},
                'spec': {'containers': {'name': 'not-a-list'}}})

    def test_unknown_kind_tolerated(self):
        Manager().validate_resource({'kind': 'MyCRD',
                                     'spec': 'anything-goes'})

    def test_add_schema(self):
        m = Manager()
        m.add_schema('MyCRD', {'spec.size': 'integer'})
        with pytest.raises(ValidationError):
            m.validate_resource({'kind': 'MyCRD',
                                 'spec': {'size': 'big'}})


class TestPolicyMutationDryRun:
    def test_valid_mutation_passes(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: ok, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: add-label
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchStrategicMerge:
          metadata:
            labels:
              +(x): "y"
"""))
        Manager().validate_policy_mutation(policy)

    def test_type_breaking_mutation_rejected(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: bad, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: break-labels
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchesJson6902: |-
          - op: add
            path: /metadata/labels
            value: "oops"
"""))
        with pytest.raises(ValidationError):
            Manager().validate_policy_mutation(policy)


class TestMutationWebhookIntegration:
    def test_schema_breaking_patch_denied(self):
        from tests.test_webhooks import make_cache, pod, review, serve
        bad_mutate = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: break-replicas
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: bad
      match: {any: [{resources: {kinds: [Deployment]}}]}
      mutate:
        patchesJson6902: |-
          - op: add
            path: /spec/replicas
            value: "three"
"""
        server = serve(make_cache(bad_mutate))
        deploy = {'apiVersion': 'apps/v1', 'kind': 'Deployment',
                  'metadata': {'name': 'd', 'namespace': 'default'},
                  'spec': {'replicas': 1}}
        body = server.handle('/mutate', json.dumps(review(deploy)).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        assert 'schema validation' in resp['status']['message']


class TestCRDSchemaSync:
    """CRD openAPIV3Schema ingestion (reference:
    pkg/controllers/openapi/controller.go:148)."""

    WIDGET_SCHEMA = {
        'type': 'object',
        'properties': {
            'spec': {
                'type': 'object',
                'properties': {
                    'size': {'type': 'integer'},
                    'name': {'type': 'string'},
                    'tags': {'type': 'array',
                             'items': {'type': 'string'}},
                    'labels': {'type': 'object',
                               'additionalProperties': {'type': 'string'}},
                    'nested': {'type': 'object', 'properties': {
                        'enabled': {'type': 'boolean'}}},
                },
            },
        },
    }

    def _client_with_crd(self):
        from kyverno_tpu.controllers.openapi import crd_fixture
        from kyverno_tpu.dclient.client import FakeClient
        client = FakeClient()
        client.create_resource(
            'apiextensions.k8s.io/v1', 'CustomResourceDefinition', '',
            crd_fixture('example.io', 'Widget', 'widgets',
                        self.WIDGET_SCHEMA))
        return client

    def test_schema_flattening(self):
        from kyverno_tpu.controllers.openapi import schema_to_fields
        fields = schema_to_fields(self.WIDGET_SCHEMA)
        assert fields['spec.size'] == 'integer'
        assert fields['spec.tags'] == 'array'
        assert fields['spec.labels'] == 'string-map'
        assert fields['spec.nested.enabled'] == 'boolean'

    def test_sync_then_validate(self):
        from kyverno_tpu.controllers.openapi import OpenAPIController
        manager = Manager()
        ctrl = OpenAPIController(self._client_with_crd(), manager)
        assert ctrl.reconcile() == 1
        manager.validate_resource({'kind': 'Widget',
                                   'spec': {'size': 3, 'name': 'w'}})
        with pytest.raises(ValidationError, match='size'):
            manager.validate_resource({'kind': 'Widget',
                                       'spec': {'size': 'big'}})

    def test_mutated_crd_instance_type_violation_rejected(self):
        """A mutation that breaks a CRD field type is denied at the
        webhook once the CRD schema is synced."""
        from kyverno_tpu.controllers.openapi import OpenAPIController
        from kyverno_tpu.policycache.cache import Cache
        from kyverno_tpu.webhooks.handlers import ResourceHandlers
        from kyverno_tpu.webhooks.server import WebhookServer
        policy = Policy({
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 'bad-mutator', 'annotations': {
                'pod-policies.kyverno.io/autogen-controllers': 'none'}},
            'spec': {'rules': [{
                'name': 'break-size',
                'match': {'any': [{'resources': {'kinds': ['Widget']}}]},
                'mutate': {'patchStrategicMerge': {
                    'spec': {'size': 'enormous'}}}}]}})
        cache = Cache()
        cache.warm_up([policy])
        handlers = ResourceHandlers(cache)
        ctrl = OpenAPIController(self._client_with_crd(),
                                 handlers.openapi_manager)
        assert ctrl.reconcile() == 1
        server = WebhookServer(handlers)
        body = server.handle('/mutate/fail', json.dumps({
            'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
            'request': {
                'uid': 'u1', 'operation': 'CREATE',
                'kind': {'group': 'example.io', 'version': 'v1',
                         'kind': 'Widget'},
                'namespace': 'default', 'name': 'w',
                'object': {'apiVersion': 'example.io/v1', 'kind': 'Widget',
                           'metadata': {'name': 'w',
                                        'namespace': 'default'},
                           'spec': {'size': 1}},
                'userInfo': {'username': 'tester'}}}).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        assert 'schema validation' in resp['status']['message']
