"""Generate/cleanup permission pre-flight (SSAR) tests.

Mirrors the reference's auth suite: pkg/auth/auth.go CanIOptions,
pkg/policy/generate/{auth.go,validate.go,validate_test.go}, and
pkg/validation/cleanuppolicy/validate.go validateAuth.
"""

import pytest

from kyverno_tpu.auth import Auth, CanI, FakeAuth, gvr_from_kind
from kyverno_tpu.background.generate import GenerateController
from kyverno_tpu.background.updaterequest import (
    STATE_FAILED, UpdateRequest, UpdateRequestGenerator,
)
from kyverno_tpu.controllers.cleanup import validate_cleanup_policy_auth
from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.policy.generate_validate import (
    GenerateValidator, validate_generate_rule,
)
from kyverno_tpu.policy.validate import PolicyValidationError, validate_policy


def deny(*denied_verbs, kinds=None):
    """Access-review hook denying specific verbs (optionally per plural)."""
    def hook(attrs):
        if attrs['verb'] in denied_verbs and \
                (kinds is None or attrs['resource'] in kinds):
            return False, f"cannot {attrs['verb']} {attrs['resource']}"
        return True, ''
    return hook


class TestGVR:
    def test_bare_kind(self):
        assert gvr_from_kind('NetworkPolicy') == ('', 'networkpolicies')
        assert gvr_from_kind('ConfigMap') == ('', 'configmaps')
        assert gvr_from_kind('Ingress') == ('', 'ingresses')

    def test_group_version_kind(self):
        assert gvr_from_kind('apps/v1/Deployment') == ('apps', 'deployments')
        assert gvr_from_kind('v1/Secret') == ('', 'secrets')
        assert gvr_from_kind('networking.k8s.io/v1/NetworkPolicy') == \
            ('networking.k8s.io', 'networkpolicies')


class TestCanI:
    def test_default_allow_all(self):
        client = FakeClient()
        assert CanI(client, 'ConfigMap', 'ns', 'create').run_access_check()

    def test_denied_verb(self):
        client = FakeClient()
        client.access_review_hook = deny('delete')
        assert CanI(client, 'ConfigMap', 'ns', 'create').run_access_check()
        assert not CanI(client, 'ConfigMap', 'ns',
                        'delete').run_access_check()

    def test_empty_kind_raises(self):
        with pytest.raises(ValueError):
            CanI(FakeClient(), '', 'ns', 'create').run_access_check()

    def test_auth_verbs(self):
        client = FakeClient()
        client.access_review_hook = deny('update', kinds={'secrets'})
        auth = Auth(client)
        assert auth.can_i_create('Secret', 'ns')
        assert not auth.can_i_update('Secret', 'ns')
        assert auth.can_i_update('ConfigMap', 'ns')


GEN_DATA_RULE = {
    'kind': 'NetworkPolicy',
    'name': 'defaultnetworkpolicy',
    'data': {'spec': {'podSelector': {},
                      'policyTypes': ['Ingress', 'Egress']}},
}


class TestGenerateValidator:
    """reference: pkg/policy/generate/validate_test.go"""

    def test_valid_data_rule_fake_auth(self):
        _, err = GenerateValidator(GEN_DATA_RULE, FakeAuth()).validate()
        assert err is None

    def test_data_and_clone_exclusive(self):
        rule = dict(GEN_DATA_RULE, clone={'name': 'x', 'namespace': 'y'})
        _, err = GenerateValidator(rule, FakeAuth()).validate()
        assert 'only one of data or clone' in err

    def test_name_required(self):
        rule = {'kind': 'ConfigMap', 'data': {}}
        path, err = GenerateValidator(rule, FakeAuth()).validate()
        assert path == 'name' and 'empty' in err

    def test_clonelist_excludes_name_kind(self):
        rule = {'cloneList': {'kinds': ['v1/Secret']}, 'name': 'x'}
        path, err = GenerateValidator(rule, FakeAuth()).validate()
        assert path == 'name' and 'cloneList' in err

    def test_denied_create_rejected(self):
        client = FakeClient()
        client.access_review_hook = deny('create')
        _, err = GenerateValidator(GEN_DATA_RULE, Auth(client)).validate()
        assert "permissions to 'create'" in err
        assert 'kyverno:generate' in err

    def test_denied_delete_rejected(self):
        client = FakeClient()
        client.access_review_hook = deny('delete')
        _, err = GenerateValidator(GEN_DATA_RULE, Auth(client)).validate()
        assert "permissions to 'delete'" in err

    def test_variable_kind_skips_auth(self):
        client = FakeClient()
        client.access_review_hook = deny('create', 'get', 'update', 'delete')
        rule = {'kind': 'ConfigMap', 'name': 'x',
                'namespace': '{{request.object.metadata.name}}',
                'data': {}}
        _, err = GenerateValidator(rule, Auth(client)).validate()
        assert err is None

    def test_clone_source_needs_get(self):
        client = FakeClient()
        client.access_review_hook = deny('get')
        rule = {'kind': 'Secret', 'name': 'tgt', 'namespace': 'ns',
                'clone': {'name': 'src', 'namespace': 'default'}}
        path, err = GenerateValidator(rule, Auth(client)).validate()
        assert "permissions to 'get'" in err

    def test_clonelist_checks_each_kind(self):
        client = FakeClient()
        client.access_review_hook = deny('update', kinds={'secrets'})
        rule = {'namespace': 'ns',
                'cloneList': {'namespace': 'default',
                              'kinds': ['v1/ConfigMap', 'v1/Secret']}}
        _, err = GenerateValidator(rule, Auth(client)).validate()
        assert "'update' resource Secret" in err


class TestPolicyValidationIntegration:
    POLICY = {
        'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
        'metadata': {'name': 'gen-netpol'},
        'spec': {'rules': [{
            'name': 'default-deny',
            'match': {'any': [{'resources': {'kinds': ['Namespace']}}]},
            'generate': {
                'apiVersion': 'networking.k8s.io/v1',
                'kind': 'NetworkPolicy', 'name': 'default-deny',
                'namespace': 'team-a',
                'data': {'spec': {'podSelector': {}}},
            }}]},
    }

    def test_policy_passes_with_permissions(self):
        assert validate_policy(self.POLICY, FakeClient()) == []

    def test_policy_rejected_without_permissions(self):
        client = FakeClient()
        client.access_review_hook = deny('create',
                                         kinds={'networkpolicies'})
        with pytest.raises(PolicyValidationError) as e:
            validate_policy(self.POLICY, client)
        assert "permissions to 'create'" in str(e.value)

    def test_variable_namespace_skips_auth(self):
        # reference: validate.go:174 — unresolved variables skip probes
        policy = {
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 'gen-netpol'},
            'spec': {'rules': [{
                'name': 'default-deny',
                'match': {'any': [{'resources': {'kinds': ['Namespace']}}]},
                'generate': {
                    'apiVersion': 'networking.k8s.io/v1',
                    'kind': 'NetworkPolicy', 'name': 'default-deny',
                    'namespace': '{{request.object.metadata.name}}',
                    'data': {'spec': {'podSelector': {}}},
                }}]},
        }
        client = FakeClient()
        client.access_review_hook = deny('create')
        assert validate_policy(policy, client) == []

    def test_offline_mode_allows(self):
        # no client → mock auth (reference: actions.go mock=true)
        assert validate_policy(self.POLICY) == []

    def test_generate_kind_matches_trigger_kind_rejected(self):
        # reference: actions.go:65
        rule = {
            'name': 'r', 'generate': {'kind': 'ConfigMap', 'name': 'x',
                                      'data': {}},
            'match': {'any': [{'resources': {'kinds': ['ConfigMap']}}]},
        }
        err = validate_generate_rule(rule, 0, None)
        assert 'should not be the same' in err


class TestURPreflight:
    """The background processor re-checks permissions before applying
    (a permission revoked after policy admission fails the UR)."""

    def _ur(self, client):
        trigger = {'apiVersion': 'v1', 'kind': 'Namespace',
                   'metadata': {'name': 'team-a'}}
        client.create_resource('v1', 'Namespace', '', trigger)
        policy = {
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 'gen-netpol'},
            'spec': {'rules': [{
                'name': 'default-deny',
                'match': {'any': [{'resources': {'kinds': ['Namespace']}}]},
                'generate': {
                    'apiVersion': 'networking.k8s.io/v1',
                    'kind': 'NetworkPolicy', 'name': 'default-deny',
                    'namespace': 'team-a',
                    'data': {'spec': {'podSelector': {}}},
                }}]},
        }
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '', policy)
        gen = UpdateRequestGenerator(client)
        gen.apply({
            'type': 'generate', 'policy': 'gen-netpol',
            'resource': {'apiVersion': 'v1', 'kind': 'Namespace',
                         'name': 'team-a', 'namespace': ''},
            'requestType': 'generate',
        })
        urs = client.list_resource('kyverno.io/v1beta1', 'UpdateRequest')
        assert urs
        return UpdateRequest(urs[0])

    def test_apply_denied_fails_ur(self):
        client = FakeClient()
        client.access_review_hook = deny('create',
                                         kinds={'networkpolicies'})
        ur = self._ur(client)
        ctrl = GenerateController(client, Engine())
        err = ctrl.process_ur(ur)
        assert err is not None
        assert "permissions to 'create'" in str(err)
        assert ur.state == STATE_FAILED
        assert not client.list_resource('networking.k8s.io/v1',
                                        'NetworkPolicy')

    def test_apply_allowed_generates(self):
        client = FakeClient()
        ur = self._ur(client)
        ctrl = GenerateController(client, Engine())
        assert ctrl.process_ur(ur) is None
        netpols = client.list_resource('networking.k8s.io/v1',
                                       'NetworkPolicy')
        assert len(netpols) == 1


class TestAuthCacheTTL:
    def test_denial_expires_after_grant(self, monkeypatch):
        monkeypatch.setenv('KTPU_AUTH_TTL', '0')
        client = FakeClient()
        client.access_review_hook = deny('create')
        ctrl = GenerateController(client, Engine())
        assert "'create'" in ctrl._check_generate_auth('ConfigMap', 'ns')
        # admin grants the permission; TTL=0 → next check re-probes
        client.access_review_hook = None
        assert ctrl._check_generate_auth('ConfigMap', 'ns') is None

    def test_group_qualified_clonelist_probe(self):
        seen = []
        client = FakeClient()

        def hook(attrs):
            seen.append((attrs['group'], attrs['resource']))
            return True, ''
        client.access_review_hook = hook
        ctrl = GenerateController(client, Engine())
        assert ctrl._check_generate_auth(
            'networking.k8s.io/v1/NetworkPolicy', 'ns') is None
        assert ('networking.k8s.io', 'networkpolicies') in seen


class TestCleanupAuth:
    DOC = {
        'apiVersion': 'kyverno.io/v2alpha1', 'kind': 'ClusterCleanupPolicy',
        'metadata': {'name': 'sweep'},
        'spec': {'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                 'schedule': '*/5 * * * *'},
    }

    def test_allowed(self):
        assert validate_cleanup_policy_auth(self.DOC, FakeClient()) is None

    def test_denied_delete(self):
        client = FakeClient()
        client.access_review_hook = deny('delete')
        err = validate_cleanup_policy_auth(self.DOC, client)
        assert 'no permission to delete kind Pod' in err

    def test_denied_list(self):
        client = FakeClient()
        client.access_review_hook = deny('list')
        err = validate_cleanup_policy_auth(self.DOC, client)
        assert 'no permission to list kind Pod' in err

    def test_cleanup_validate_route(self):
        """POST /validate on the cleanup daemon rejects a CleanupPolicy
        the controller lacks delete permission for."""
        import json
        import urllib.request
        from kyverno_tpu.cmd.cleanup_controller import CleanupHTTPServer
        from kyverno_tpu.controllers.cleanup import CleanupController
        client = FakeClient()
        client.access_review_hook = deny('delete')
        server = CleanupHTTPServer(CleanupController(client), host='127.0.0.1')
        port = server.start()
        try:
            review = {'request': {'uid': 'u1', 'object': self.DOC}}
            resp = json.load(urllib.request.urlopen(urllib.request.Request(
                f'http://127.0.0.1:{port}/validate',
                json.dumps(review).encode(),
                {'Content-Type': 'application/json'})))
            r = resp['response']
            assert r['allowed'] is False
            assert 'no permission to delete' in r['status']['message']
            client.access_review_hook = None
            resp = json.load(urllib.request.urlopen(urllib.request.Request(
                f'http://127.0.0.1:{port}/validate',
                json.dumps(review).encode(),
                {'Content-Type': 'application/json'})))
            assert resp['response']['allowed'] is True
        finally:
            server.stop()


class TestPluralize:
    """SSAR probes must target real GVRs: -ies only after a consonant,
    irregulars from the table (the old rule produced 'gatewaies')."""

    def test_consonant_y_takes_ies(self):
        assert gvr_from_kind('NetworkPolicy')[1] == 'networkpolicies'
        assert gvr_from_kind('Proxy')[1] == 'proxies'

    def test_vowel_y_takes_plain_s(self):
        assert gvr_from_kind('Gateway')[1] == 'gateways'
        assert gvr_from_kind('gateway.networking.k8s.io/v1/Gateway') == \
            ('gateway.networking.k8s.io', 'gateways')

    def test_irregular_table(self):
        assert gvr_from_kind('Endpoints')[1] == 'endpoints'
        assert gvr_from_kind('PodMetrics')[1] == 'pods'
        assert gvr_from_kind('ReferenceGrant')[1] == 'referencegrants'

    def test_sibilant_suffixes(self):
        assert gvr_from_kind('Ingress')[1] == 'ingresses'
        assert gvr_from_kind('ConfigMap')[1] == 'configmaps'
