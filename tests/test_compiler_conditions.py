"""Device-vs-host equivalence for the v2 compiler surface:
deny / preconditions / anyPattern / condition operators / scalar arrays.

Every policy here must fully compile (no host-rule fallback) so the device
path is genuinely exercised; the scanner may still re-run individual
(resource, rule) pairs flagged HOST, which is part of the contract under
test — results must be bit-identical to a pure host run either way.
"""

import random

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: precond-deny
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: deny-default-ns
      match: {any: [{resources: {kinds: [Pod]}}]}
      preconditions:
        all:
          - key: "{{request.object.metadata.namespace}}"
            operator: NotEquals
            value: kube-system
      validate:
        message: "default namespace is denied"
        deny:
          conditions:
            any:
              - key: "{{request.object.metadata.namespace}}"
                operator: Equals
                value: default
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: anyin-registries
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: registries
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "unknown registry"
        deny:
          conditions:
            all:
              - key: "{{request.object.spec.containers[].image}}"
                operator: AnyNotIn
                value: ["ghcr.io/*", "docker.io/*", "nginx*"]
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: numeric-conditions
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: replica-limit
      match: {any: [{resources: {kinds: [Deployment]}}]}
      preconditions:
        all:
          - key: "{{request.object.spec.replicas}}"
            operator: GreaterThan
            value: 0
      validate:
        message: "too many replicas"
        deny:
          conditions:
            any:
              - key: "{{request.object.spec.replicas}}"
                operator: GreaterThan
                value: 10
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: any-pattern
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: reg-or-tag
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "need registry or explicit tag"
        anyPattern:
          - spec:
              containers:
                - image: "ghcr.io/*"
          - spec:
              containers:
                - image: "*:v?*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: range-conditions
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: port-range
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "ports out of range"
        deny:
          conditions:
            all:
              - key: "{{request.object.spec.containers[].ports[].containerPort}}"
                operator: AnyNotIn
                value: "1024-65535"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: scalar-array
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: finalizer-prefix
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "bad finalizers"
        pattern:
          metadata:
            finalizers:
              - "kyverno.io/*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: equals-shapes
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: host-network-eq
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "hostNetwork must be false-ish"
        deny:
          conditions:
            any:
              - key: "{{request.object.spec.hostNetwork}}"
                operator: Equals
                value: true
              - key: "{{request.object.spec.priority}}"
                operator: Equals
                value: 1000000
              - key: "{{request.object.spec.schedulerName}}"
                operator: Equals
                value: "evil-*"
"""


def load_pack():
    return [Policy(d) for d in yaml.safe_load_all(PACK)]


def make_pod(rng):
    containers = []
    for i in range(rng.randint(1, 4)):
        c = {'name': f'c{i}',
             'image': rng.choice([
                 'nginx:1.25', 'nginx:latest', 'ghcr.io/a/b:v1', 'redis',
                 'docker.io/library/nginx', 'quay.io/x/y:v2.0', '',
                 'nginx', 'app:v3'])}
        if rng.random() < 0.6:
            c['ports'] = [
                {'containerPort': rng.choice(
                    [80, 443, 1024, 8080, 65535, 65536, 22, '8080'])}
                for _ in range(rng.randint(1, 3))]
        containers.append(c)
    pod = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': f'p{rng.randint(0, 999)}',
                        'namespace': rng.choice(
                            ['default', 'kube-system', 'apps', ''])},
           'spec': {'containers': containers}}
    if rng.random() < 0.3:
        pod['spec']['hostNetwork'] = rng.choice([True, False, 'true', 1])
    if rng.random() < 0.3:
        pod['spec']['priority'] = rng.choice(
            [1000000, 0, 999999, '1000000', 1000000.0])
    if rng.random() < 0.3:
        pod['spec']['schedulerName'] = rng.choice(
            ['evil-scheduler', 'default-scheduler', 'evil-', 'x'])
    if rng.random() < 0.4:
        pod['metadata']['finalizers'] = rng.sample(
            ['kyverno.io/cleanup', 'kyverno.io/x', 'other.io/y', 'plain'],
            rng.randint(1, 3))
    if rng.random() < 0.1:
        del pod['spec']['containers']
    return pod


def make_deployment(rng):
    spec = {}
    r = rng.choice([0, 1, 5, 10, 11, '3', '12', None, True, 10.0, 10.5])
    if r is not None:
        spec['replicas'] = r
    return {'apiVersion': 'apps/v1', 'kind': 'Deployment',
            'metadata': {'name': 'd', 'namespace': 'default'}, 'spec': spec}


def host_results(engine, policies, resource):
    host = {}
    for policy in policies:
        resp = engine.apply_background_checks(
            PolicyContext(policy, new_resource=resource))
        if resp.policy_response.rules:
            host[policy.name] = {
                r.name: (r.status, r.message)
                for r in resp.policy_response.rules}
    return host


class TestConditionCompile:
    def test_pack_fully_compiles(self):
        cps = compile_policies(load_pack())
        assert cps.host_rules == [], \
            [r.get('name') for _, r, _ in cps.host_rules]
        assert len(cps.programs) == 7


class TestConditionEquivalence:
    def test_device_vs_host_fuzz(self):
        policies = load_pack()
        engine = Engine()
        rng = random.Random(11)
        resources = [make_pod(rng) for _ in range(120)] + \
                    [make_deployment(rng) for _ in range(40)]
        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)
        for resource, responses in zip(resources, scanned):
            host = host_results(engine, policies, resource)
            got = {}
            for resp in responses:
                if resp.policy_response.rules:
                    got[resp.policy_response.policy_name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            assert got == host, f'divergence on {resource}'

    def test_device_decides_most(self):
        """The device must answer (not host-fallback) the bulk of the
        simple verdicts, or the compiled path is useless."""
        from kyverno_tpu.compiler.ir import STATUS_HOST
        policies = load_pack()
        rng = random.Random(13)
        resources = [make_pod(rng) for _ in range(100)]
        scanner = BatchScanner(policies)
        status, detail, match = scanner.scan_statuses(resources)
        applicable = match.sum()
        host_rate = (match & (status == STATUS_HOST)).sum() / max(
            applicable, 1)
        assert host_rate < 0.1, f'device host-fallback rate {host_rate:.2f}'


class TestReviewRegressions:
    """Divergences caught by adversarial review of the device operators."""

    def _one_cond_policy(self, key, operator, value):
        import yaml as _yaml
        doc = {
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 't', 'annotations': {
                'pod-policies.kyverno.io/autogen-controllers': 'none'}},
            'spec': {'rules': [{
                'name': 'r',
                'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                'validate': {'message': 'm', 'deny': {'conditions': {
                    'any': [{'key': key, 'operator': operator,
                             'value': value}]}}}}]}}
        return Policy(doc)

    def _check(self, policy, resource):
        engine = Engine()
        host = engine.apply_background_checks(
            PolicyContext(policy, new_resource=resource))
        hmap = {r.name: (r.status, r.message)
                for r in host.policy_response.rules}
        scanner = BatchScanner([policy])
        [resp_list] = scanner.scan([resource])
        dmap = {}
        for resp in resp_list:
            dmap.update({r.name: (r.status, r.message)
                         for r in resp.policy_response.rules})
        assert dmap == hmap, (dmap, hmap)

    def _pod(self, **labels):
        return {'apiVersion': 'v1', 'kind': 'Pod',
                'metadata': {'name': 'p', 'namespace': 'd',
                             'labels': labels},
                'spec': {'containers': [{'name': 'c', 'image': 'x'}]}}

    def test_float_string_key_vs_duration_value(self):
        p = self._one_cond_policy('{{request.object.metadata.labels.x}}',
                                  'LessThan', '10s')
        self._check(p, self._pod(x='1.5'))
        self._check(p, self._pod(x='15'))
        self._check(p, self._pod(x='0.3'))

    def test_numeric_float_trunc_boundary(self):
        # host: int(0.3 * 1e9) == 299999999 — the device must reproduce
        # the same float64 truncation
        p = self._one_cond_policy('{{request.object.metadata.labels.x}}',
                                  'LessThan', 0.3)
        self._check(p, self._pod(x='300ms'))
        self._check(p, self._pod(x='299999999ns'))

    def test_equals_float_value_vs_duration_key(self):
        p = self._one_cond_policy('{{request.object.metadata.labels.x}}',
                                  'Equals', 1.000000007)
        self._check(p, self._pod(x='1000000006ns'))
        self._check(p, self._pod(x='1000000007ns'))

    def test_single_elem_list_json_literal_shortcut(self):
        p = self._one_cond_policy(
            '{{request.object.spec.containers[].image}}',
            'AllIn', '["a","b"]')
        pod = self._pod()
        pod['spec']['containers'] = [{'name': 'c', 'image': '["a","b"]'}]
        self._check(p, pod)
        pod['spec']['containers'] = [{'name': 'c', 'image': 'a'}]
        self._check(p, pod)

    def test_allnotin_universal(self):
        # reference isAllNotIn (allin.go:192) is universal: false when ANY
        # key element matches any value element
        p = self._one_cond_policy(
            '{{request.object.spec.containers[].image}}',
            'AllNotIn', ['a', 'b'])
        pod = self._pod()
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'a'},
                                     {'name': 'c1', 'image': 'z'}]
        self._check(p, pod)  # 'a' matches → AllNotIn false → no deny
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'y'},
                                     {'name': 'c1', 'image': 'z'}]
        self._check(p, pod)  # nothing matches → AllNotIn true → deny

    def test_allnotin_json_string_wildcards(self):
        # JSON-string values run the same bidirectional wildcard
        # membership as list values (allin.go:168-170)
        p = self._one_cond_policy(
            '{{request.object.spec.containers[].image}}',
            'AllNotIn', '["nginx*"]')
        pod = self._pod()
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'nginx:1'},
                                     {'name': 'c1', 'image': 'redis:7'}]
        self._check(p, pod)
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'redis:7'}]
        self._check(p, pod)

    def test_anyin_json_string_wildcards(self):
        p = self._one_cond_policy(
            '{{request.object.spec.containers[].image}}',
            'AnyIn', '["ghcr.io/*"]')
        pod = self._pod()
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'ghcr.io/a'}]
        self._check(p, pod)
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'quay.io/a'}]
        self._check(p, pod)

    def test_in_family_wildcard_key_value_json_string(self):
        # the KEY side may carry wildcard chars that match the value as a
        # pattern (anyin.go:193 wildcard.Match(valKey, valValue))
        p = self._one_cond_policy(
            '{{request.object.spec.containers[].image}}',
            'AnyIn', '["nginx:1"]')
        pod = self._pod()
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'nginx:*'}]
        self._check(p, pod)
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'nginx:1'}]
        self._check(p, pod)

    def test_in_family_suffix_element_pattern(self):
        # suffix-classified JSON elements must provision the tail lane
        p = self._one_cond_policy(
            '{{request.object.spec.containers[].image}}',
            'AnyIn', '["*nginx"]')
        pod = self._pod()
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'my-nginx'}]
        self._check(p, pod)
        pod['spec']['containers'] = [{'name': 'c0', 'image': 'redis'}]
        self._check(p, pod)

    def test_empty_scan_statuses(self):
        scanner = BatchScanner(load_pack())
        status, detail, match = scanner.scan_statuses([])
        assert status.shape[0] == 0 and match.shape[0] == 0
