"""Streaming scan pipeline: bounded overlapped stages + columnar encode
+ incremental report assembly.

Pins the tentpole contracts of the streaming rebuild:

* streaming output is byte-identical to the dense oracle at every chunk
  boundary shape (1, cap−1, cap, cap+1, 3·cap+1);
* host memory stays bounded while a 50k-row synthetic scan streams
  (tracemalloc, not RSS — allocator noise-free);
* a slow d2h leg BACKPRESSURES the pipeline (bounded queues, counted on
  kyverno_tpu_scan_backpressure_seconds_total) instead of buffering;
* the d2h stall watchdog and the flight-recorder dump still fire when
  the readback runs on a pipeline worker thread;
* verdict-cache replays interleave with miss chunks through the
  streaming reconcile.
"""

import json
import os
import random
import sys
import time
import tracemalloc

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402
from kyverno_tpu.api.policy import Policy, load_policies_from_yaml  # noqa: E402
from kyverno_tpu.compiler.scan import BatchScanner  # noqa: E402
from kyverno_tpu.observability import device as devtel  # noqa: E402
from kyverno_tpu.observability import provenance  # noqa: E402
from kyverno_tpu.observability.metrics import MetricsRegistry  # noqa: E402
from kyverno_tpu.reports.types import build_fused_report  # noqa: E402

CAP = 16  # tiny chunk capacity so a handful of pods spans many chunks


def pods(n, seed=5):
    rng = random.Random(seed)
    return [bench.make_pod(rng, i) for i in range(n)]


@pytest.fixture(scope='module')
def policies():
    return load_policies_from_yaml(bench.PACK)


@pytest.fixture()
def small_chunk_scanner(policies):
    scanner = BatchScanner(policies)
    scanner.CHUNK = CAP
    return scanner


def reports_of(scanner, docs, now=1234.0):
    return [build_fused_report(doc, *row)
            for doc, row in zip(docs, scanner.scan_report_results(
                docs, now=now))]


class TestChunkBoundaryIdentity:
    @pytest.mark.parametrize('n', [1, CAP - 1, CAP, CAP + 1, 3 * CAP + 1])
    def test_streaming_matches_dense_oracle(self, policies,
                                            small_chunk_scanner, n):
        """The multi-chunk pipeline at a tiny capacity produces reports
        byte-identical to the dense single-chunk oracle, in input
        order, at every boundary shape."""
        docs = pods(n)
        dense = BatchScanner(policies)   # default CHUNK: one chunk
        assert n <= dense.CHUNK
        expect = reports_of(dense, docs)
        got = reports_of(small_chunk_scanner, docs)
        assert len(got) == n
        assert got == expect

    def test_streaming_matches_unfused_responses(self, policies,
                                                 small_chunk_scanner):
        """Fused streaming rows == the unfused scan_stream +
        set_responses path across a chunk boundary (the report-fusion
        oracle, exercised through the pipeline)."""
        from kyverno_tpu.reports.results import set_responses
        from kyverno_tpu.reports.types import new_background_scan_report
        docs = pods(2 * CAP + 3)
        unfused = []
        for doc, responses in zip(docs,
                                  small_chunk_scanner.scan_stream(docs)):
            report = new_background_scan_report(doc)
            relevant = [r for r in responses if r.policy_response.rules]
            set_responses(report, *relevant)
            unfused.append(report)
        fused = reports_of(small_chunk_scanner, docs)
        assert len(fused) == len(unfused)

        def strip_ts(results):
            return [{k: v for k, v in r.items() if k != 'timestamp'}
                    for r in results]
        for f, u in zip(fused, unfused):
            assert f['metadata'].get('labels') == \
                u['metadata'].get('labels')
            assert f['spec']['summary'] == u['spec']['summary']
            assert strip_ts(f['spec']['results']) == \
                strip_ts(u['spec']['results'])


class TestBoundedMemory:
    def test_50k_scan_streams_in_bounded_memory(self, policies):
        """Python-heap growth while 50k rows stream through the report
        path stays at O(chunk), not O(n): the arena recycles lane
        tensors and rows flush as chunks land."""
        scanner = BatchScanner(policies)
        scanner.CHUNK = 4096
        docs = pods(50_000, seed=11)
        # warm: compile + allocate the arena outside the measurement
        for _ in scanner.scan_report_results(docs[:8192]):
            pass
        tracemalloc.start()
        base, _ = tracemalloc.get_traced_memory()
        n_rows = 0
        for _row in scanner.scan_report_results(docs):
            n_rows += 1
        _cur, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert n_rows == len(docs)
        growth_mb = (peak - base) / 1e6
        # 50k decoded rows at ~2KB each would be ≥100MB; the streaming
        # path must hold only a few chunks of lanes + one flush window
        assert growth_mb < 64, f'heap grew {growth_mb:.1f}MB over stream'


class _SlowReadback:
    """Wraps a jax output array; np.array() pays an injected delay —
    an artificially slowed d2h leg."""

    def __init__(self, arr, delay_s):
        self._arr = arr
        self._delay_s = delay_s

    def __array__(self, dtype=None, copy=None):
        time.sleep(self._delay_s)
        out = np.asarray(self._arr)
        return out.astype(dtype) if dtype is not None else out


def _slow_d2h(scanner, delay_s):
    inner = scanner._evaluator

    def slow(t, layout):
        return [_SlowReadback(o, delay_s) for o in inner(t, layout)]
    for attr in ('adm_cols', 'n_uniq', 'any_meta', 'n_cols_u', 'uniq_idx',
                 'expand_idx', 'expand_identity', 'adm_table'):
        setattr(slow, attr, getattr(inner, attr, None))
    slow.n_adm = getattr(inner, 'n_adm', 0)
    scanner._evaluator = slow
    return inner


class TestBackpressure:
    def test_slow_d2h_backpressures_intake(self, policies):
        """With the d2h leg artificially slowed, the bounded queues
        push back on the upstream stages: blocked time lands on the
        backpressure counter, the in-flight gauge tops out at
        KTPU_PIPELINE_DEPTH, and output is still complete and
        in-order."""
        registry = MetricsRegistry()
        devtel.configure(registry)
        try:
            scanner = BatchScanner(policies)
            scanner.CHUNK = CAP
            docs = pods(8 * CAP)
            for _ in scanner.scan_report_results(docs[:CAP]):
                pass  # warm the executable so the slow run measures d2h
            _slow_d2h(scanner, 0.05)
            rows = list(scanner.scan_report_results(docs))
            assert len(rows) == len(docs)
            total_bp = registry.counter_total(
                'kyverno_tpu_scan_backpressure_seconds_total')
            assert total_bp > 0.0, \
                'slow d2h produced no backpressure accounting'
            # the gauge always resets when the stream ends
            assert registry.gauge_value(
                'kyverno_tpu_scan_pipeline_inflight_chunks') == 0.0
        finally:
            devtel.disable()


class TestWatchdogFromWorkers:
    def test_stall_watchdog_fires_on_pipeline_thread(self, policies,
                                                     tmp_path):
        """A stalled readback inside the pipeline's d2h worker still
        trips the watchdog AND the flight-recorder dump — the
        provenance capture and event-sink chain survive the move onto
        worker threads."""
        registry = MetricsRegistry()
        devtel.configure(registry, stall_threshold_s=0.02)
        recorder = provenance.configure(registry, flight_n=8,
                                        dump_dir=str(tmp_path))
        events = []
        devtel.add_event_sink(events.append)
        try:
            scanner = BatchScanner(policies)
            scanner.CHUNK = CAP
            docs = pods(3 * CAP)
            for _ in scanner.scan_report_results(docs[:CAP]):
                pass  # warm compile outside the stall window
            _slow_d2h(scanner, 0.2)
            cap = devtel.ScanCapture()
            with devtel.install_capture(cap):
                rows = list(scanner.scan_report_results(docs))
            assert len(rows) == len(docs)
            stalls = [e for e in events if e.get('type') == 'd2h_stall']
            assert stalls, 'watchdog never fired from the worker thread'
            assert registry.counter_total(
                'kyverno_tpu_d2h_stalls_total') >= 1
            # the flight recorder dumped on the same event chain
            assert recorder.dump_paths, 'no flight-recorder dump'
            lines = [json.loads(x) for x in open(recorder.dump_paths[0])]
            assert lines[0]['trigger'] == 'd2h_stall'
            # stage time kept flowing into the installed capture from
            # the worker threads (provenance threading preserved)
            assert cap.stage_s('d2h') > 0.0
            assert cap.stage_s('encode') > 0.0
        finally:
            devtel.remove_event_sink(events.append)
            provenance.disable()
            devtel.disable()


class TestReplayInterleavedWithMisses:
    def test_reconcile_replays_between_miss_chunks(self, tmp_path,
                                                   monkeypatch):
        """A reconcile whose pending set mixes cache hits and misses
        spanning several device chunks replays the hits inline and
        streams the misses — reports byte-identical to a cache-off
        dense reconcile."""
        from kyverno_tpu.dclient.client import FakeClient
        from kyverno_tpu.reports.controllers import (
            BackgroundScanController)
        monkeypatch.setenv('KTPU_VERDICT_CACHE_DIR', str(tmp_path / 'vc'))
        policies = load_policies_from_yaml(bench.PACK)
        docs = pods(3 * CAP + 5, seed=17)
        for i, d in enumerate(docs):
            d['metadata']['uid'] = f'uid-{i}'

        def build(enabled):
            monkeypatch.setenv('KTPU_VERDICT_CACHE',
                               '1' if enabled else '0')
            ctrl = BackgroundScanController(FakeClient(), policies)
            ctrl.scanner.CHUNK = CAP
            return ctrl

        ctrl = build(True)
        for d in docs:
            ctrl.enqueue(d)
        ctrl.reconcile(now=2000.0)  # cold tick: populate the cache
        # mutate a slice spread across chunk boundaries → misses, the
        # rest replays
        changed = list(range(0, len(docs), 3))
        for i in changed:
            docs[i]['spec']['containers'][0]['image'] = f'churn:{i}'
        ctrl.reset_scan_state()
        for d in docs:
            ctrl.enqueue(d)
        reports = ctrl.reconcile(now=2031.0)
        assert ctrl.rescan_stats['rows_scanned'] == len(changed)
        assert ctrl.rescan_stats['rows_replayed'] == \
            len(docs) - len(changed)

        dense = build(False)
        for d in docs:
            dense.enqueue(d)
        dense_reports = dense.reconcile(now=2031.0)

        def content(r):
            # strip fake-server bookkeeping (resourceVersion/uid differ
            # between create and update writes); everything the scan
            # produced must match exactly
            meta = {k: v for k, v in r['metadata'].items()
                    if k not in ('resourceVersion', 'uid')}
            return dict(r, metadata=meta)

        key = lambda r: r['metadata']['name']  # noqa: E731
        assert [content(r) for r in sorted(reports, key=key)] == \
            [content(r) for r in sorted(dense_reports, key=key)]
