"""Device-pipeline telemetry: stage spans over the batched scan path,
compile-cache counters, the d2h stall watchdog, and the zero-overhead
no-op guarantees when tracing/metrics are unconfigured."""

import threading
import time

import pytest

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import device as devtel
from kyverno_tpu.observability import tracing
from kyverno_tpu.observability.metrics import MetricsRegistry

POLICY = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'require-labels', 'annotations': {
        'pod-policies.kyverno.io/autogen-controllers': 'none'}},
    'spec': {'validationFailureAction': 'Enforce', 'rules': [
        {'name': 'check-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'app label required',
                      'pattern': {'metadata': {'labels': {'app': '?*'}}}}},
    ]}}


def pod(i):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{i}', 'namespace': 'default',
                         'labels': {'app': 'x'} if i % 2 else {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}


@pytest.fixture
def telemetry():
    mem = tracing.configure()
    reg = devtel.configure(MetricsRegistry())
    yield mem, reg
    devtel.disable()
    tracing.disable()


@pytest.fixture
def scanner():
    from kyverno_tpu.compiler.scan import BatchScanner
    return BatchScanner([Policy(POLICY)])


def _watchdog_threads():
    return [t for t in threading.enumerate()
            if t.name == 'ktpu-d2h-watchdog']


class TestStageSpans:
    def test_scan_emits_all_stages(self, telemetry, scanner):
        mem, reg = telemetry
        # first scan pays the compile stage; the second hits the cached
        # executable and runs as device_eval
        scanner.scan([pod(i) for i in range(8)])
        scanner.scan([pod(i) for i in range(8)])
        names = {s.name for s in mem.spans()}
        assert 'kyverno/device/compile' in names
        assert reg.histogram_count(
            'kyverno_tpu_scan_stage_duration_seconds',
            stage='compile') >= 1
        for stage in ('encode', 'pack', 'h2d', 'device_eval', 'd2h',
                      'report'):
            assert f'kyverno/device/{stage}' in names, stage
            assert reg.histogram_count(
                'kyverno_tpu_scan_stage_duration_seconds',
                stage=stage) >= 1, stage

    def test_stage_spans_join_one_trace(self, telemetry, scanner):
        """request root → chunk wrapper → device stage spans all carry
        one trace id (the single-trace requirement of the pipeline)."""
        mem, _reg = telemetry
        scanner.scan([pod(i) for i in range(4)])  # warm the executable
        with tracing.start_span('request-root') as root:
            scanner.scan([pod(i) for i in range(4)])
        by_name = {}
        for s in mem.spans():
            by_name.setdefault(s.name, []).append(s)
        [chunk] = [s for s in by_name['kyverno/device/chunk']
                   if s.trace_id == root.trace_id]
        # the chunk wrapper nests under the per-chunk scan span, which
        # nests under the request root
        parents = {s.span_id: s for s in mem.spans()}
        scan_span = parents[chunk.parent_id]
        assert scan_span.name == 'kyverno/device/scan'
        assert scan_span.parent_id == root.span_id
        for stage in ('pack', 'h2d', 'device_eval', 'd2h'):
            stage_spans = [s for s in by_name[f'kyverno/device/{stage}']
                           if s.trace_id == root.trace_id]
            assert stage_spans, stage
            assert all(s.parent_id == chunk.span_id
                       for s in stage_spans), stage

    def test_compile_cache_counters(self, telemetry):
        _mem, reg = telemetry
        from kyverno_tpu.compiler.scan import BatchScanner
        fresh = BatchScanner([Policy(POLICY)])
        fresh.scan([pod(i) for i in range(4)])   # compiles or aot-loads
        fresh.scan([pod(i) for i in range(4)])   # memory hit
        total = reg.counter_total(
            'kyverno_tpu_compile_cache_requests_total')
        hits = reg.counter_value(
            'kyverno_tpu_compile_cache_requests_total', result='hit')
        assert total >= 2
        assert hits >= 1
        text = reg.render()
        assert 'kyverno_tpu_compile_cache_requests_total' in text
        assert 'result="hit"' in text

    def test_batch_size_and_d2h_bytes(self, telemetry, scanner):
        _mem, reg = telemetry
        scanner.scan([pod(i) for i in range(8)])
        assert reg.gauge_value('kyverno_tpu_device_batch_size') == 8.0
        assert reg.counter_total('kyverno_tpu_d2h_bytes_total') > 0


class TestWatchdog:
    def test_fires_on_delayed_d2h(self):
        fired = []
        tracing.disable()
        reg = devtel.configure(MetricsRegistry(), stall_threshold_s=0.05,
                               event_sink=fired.append)
        try:
            with devtel.d2h_guard({'chunk_start': 0}):
                time.sleep(0.25)  # artificially delayed readback
            deadline = time.time() + 2.0
            while not fired and time.time() < deadline:
                time.sleep(0.01)
            assert reg.counter_total('kyverno_tpu_d2h_stalls_total') == 1
            [event] = fired
            assert event['type'] == 'd2h_stall'
            assert event['elapsed_s'] >= 0.05
            assert event['chunk_start'] == 0
            assert devtel.watchdog().stall_events
        finally:
            devtel.disable()

    def test_silent_under_threshold(self):
        fired = []
        reg = devtel.configure(MetricsRegistry(), stall_threshold_s=0.5,
                               event_sink=fired.append)
        try:
            for _ in range(3):
                with devtel.d2h_guard():
                    time.sleep(0.01)
            time.sleep(0.2)  # give the monitor a chance to misfire
            assert reg.counter_total('kyverno_tpu_d2h_stalls_total') == 0
            assert not fired
        finally:
            devtel.disable()

    def test_fires_once_per_stall(self):
        reg = devtel.configure(MetricsRegistry(), stall_threshold_s=0.03)
        try:
            with devtel.d2h_guard():
                time.sleep(0.2)
            time.sleep(0.1)
            assert reg.counter_total('kyverno_tpu_d2h_stalls_total') == 1
        finally:
            devtel.disable()

    def test_env_default_threshold(self, monkeypatch):
        monkeypatch.setenv('KTPU_D2H_STALL_S', '7.5')
        devtel.configure(MetricsRegistry())
        try:
            assert devtel.watchdog().threshold_s == 7.5
        finally:
            devtel.disable()

    def test_thread_stops_on_disable(self):
        devtel.configure(MetricsRegistry(), stall_threshold_s=10.0)
        token = devtel.watchdog().arm()
        assert _watchdog_threads()
        devtel.watchdog().disarm(token)
        devtel.disable()
        deadline = time.time() + 2.0
        while _watchdog_threads() and time.time() < deadline:
            time.sleep(0.01)
        assert not _watchdog_threads()

    def test_stop_clears_thread_under_lock(self):
        """`stop()` must write `_thread` under the condition variable
        (arm() reads and writes it there); after stop the slot is
        cleared, a second stop is a no-op, and a post-stop arm is
        refused without resurrecting the thread."""
        from kyverno_tpu.observability.device import D2HWatchdog
        wd = D2HWatchdog(threshold_s=10.0)
        token = wd.arm()
        assert token >= 0 and wd._thread is not None
        wd.disarm(token)
        wd.stop()
        assert wd._thread is None
        wd.stop()  # idempotent
        assert wd.arm() == -1  # stopped watchdogs refuse new arms
        assert wd._thread is None


class TestNoopWhenUnconfigured:
    def test_scan_allocates_nothing(self, scanner):
        tracing.disable()
        devtel.disable()
        before = set(threading.enumerate())
        scanner.scan([pod(i) for i in range(8)])
        assert tracing.memory_exporter() is None
        assert devtel.registry() is None
        assert devtel.watchdog() is None
        assert not _watchdog_threads()
        # only the scan pipeline's own executor threads may appear —
        # no telemetry thread survives the call
        after = {t for t in threading.enumerate() if t not in before}
        assert not any(t.name == 'ktpu-d2h-watchdog' for t in after)
        assert devtel.stage_breakdown() == {}

    def test_stage_returns_shared_noop(self):
        tracing.disable()
        devtel.disable()
        s1 = devtel.stage('pack')
        s2 = devtel.stage('d2h')
        g = devtel.d2h_guard()
        assert s1 is s2 is g  # one shared no-op object, no allocation
        with s1:
            s1.set_attribute('k', 'v')
            s1.add_d2h_bytes(10)

    def test_tracing_only_emits_spans_not_series(self, scanner):
        devtel.disable()
        mem = tracing.configure()
        try:
            scanner.scan([pod(i) for i in range(4)])
            assert any(s.name.startswith('kyverno/device/')
                       for s in mem.spans())
            assert devtel.registry() is None
        finally:
            tracing.disable()
