"""One contract suite, two clients (VERDICT r3 #6): every test runs
against BOTH the in-memory FakeClient and the HTTP transport speaking to
the in-process fake API server (kyverno_tpu/dclient/fakeserver.py, which
wraps a FakeClient store) — so the REST mapping, error taxonomy, and
selector plumbing are exercised end to end.

Reference surface: pkg/clients/dclient/client.go:22.
"""

import threading
import time

import pytest

from kyverno_tpu.dclient.client import (AlreadyExistsError, ConflictError,
                                        FakeClient, NotFoundError)
from kyverno_tpu.dclient.fakeserver import FakeApiServer
from kyverno_tpu.dclient.http_client import (ClusterConfig, HTTPClient,
                                             load_kubeconfig)


def pod(name, ns='default', labels=None):
    meta = {'name': name, 'namespace': ns}
    if labels:
        meta['labels'] = labels
    return {'apiVersion': 'v1', 'kind': 'Pod', 'metadata': meta,
            'spec': {'containers': [{'name': 'c', 'image': 'i'}]}}


@pytest.fixture(params=['fake', 'http'])
def client(request):
    if request.param == 'fake':
        yield FakeClient()
        return
    with FakeApiServer() as srv:
        c = HTTPClient(ClusterConfig(server=srv.url))
        yield c
        c.close()


class TestContract:
    def test_create_get_roundtrip(self, client):
        client.create_resource('v1', 'Pod', 'default', pod('a'))
        got = client.get_resource('v1', 'Pod', 'default', 'a')
        assert got['metadata']['name'] == 'a'
        assert got['metadata']['resourceVersion']

    def test_get_missing_raises_not_found(self, client):
        with pytest.raises(NotFoundError):
            client.get_resource('v1', 'Pod', 'default', 'nope')

    def test_create_duplicate_raises_already_exists(self, client):
        client.create_resource('v1', 'Pod', 'default', pod('a'))
        with pytest.raises(AlreadyExistsError):
            client.create_resource('v1', 'Pod', 'default', pod('a'))

    def test_update_bumps_resource_version(self, client):
        client.create_resource('v1', 'Pod', 'default', pod('a'))
        got = client.get_resource('v1', 'Pod', 'default', 'a')
        rv1 = got['metadata']['resourceVersion']
        got['metadata']['labels'] = {'x': 'y'}
        out = client.update_resource('v1', 'Pod', 'default', got)
        assert out['metadata']['resourceVersion'] != rv1

    def test_stale_update_conflicts(self, client):
        client.create_resource('v1', 'Pod', 'default', pod('a'))
        stale = client.get_resource('v1', 'Pod', 'default', 'a')
        fresh = client.get_resource('v1', 'Pod', 'default', 'a')
        fresh['metadata']['labels'] = {'x': '1'}
        client.update_resource('v1', 'Pod', 'default', fresh)
        stale['metadata']['labels'] = {'x': '2'}
        with pytest.raises(ConflictError):
            client.update_resource('v1', 'Pod', 'default', stale)

    def test_update_missing_raises_not_found(self, client):
        with pytest.raises(NotFoundError):
            client.update_resource('v1', 'Pod', 'default', pod('ghost'))

    def test_delete_then_get_raises(self, client):
        client.create_resource('v1', 'Pod', 'default', pod('a'))
        client.delete_resource('v1', 'Pod', 'default', 'a')
        with pytest.raises(NotFoundError):
            client.get_resource('v1', 'Pod', 'default', 'a')

    def test_delete_missing_raises(self, client):
        with pytest.raises(NotFoundError):
            client.delete_resource('v1', 'Pod', 'default', 'nope')

    def test_dry_run_create_stores_nothing(self, client):
        client.create_resource('v1', 'Pod', 'default', pod('a'),
                               dry_run=True)
        with pytest.raises(NotFoundError):
            client.get_resource('v1', 'Pod', 'default', 'a')

    def test_list_namespace_scoping(self, client):
        client.create_resource('v1', 'Pod', 'a', pod('p1', ns='a'))
        client.create_resource('v1', 'Pod', 'b', pod('p2', ns='b'))
        names = [p['metadata']['name']
                 for p in client.list_resource('v1', 'Pod', 'a')]
        assert names == ['p1']
        both = client.list_resource('v1', 'Pod')
        assert len(both) == 2

    def test_list_label_selector(self, client):
        client.create_resource('v1', 'Pod', 'default',
                               pod('red', labels={'color': 'red'}))
        client.create_resource('v1', 'Pod', 'default',
                               pod('blue', labels={'color': 'blue'}))
        sel = {'matchLabels': {'color': 'red'}}
        names = [p['metadata']['name']
                 for p in client.list_resource('v1', 'Pod', 'default', sel)]
        assert names == ['red']

    def test_list_match_expressions(self, client):
        client.create_resource('v1', 'Pod', 'default',
                               pod('red', labels={'color': 'red'}))
        client.create_resource('v1', 'Pod', 'default',
                               pod('blue', labels={'color': 'blue'}))
        client.create_resource('v1', 'Pod', 'default', pod('plain'))
        sel = {'matchExpressions': [
            {'key': 'color', 'operator': 'In',
             'values': ['red', 'green']}]}
        names = [p['metadata']['name']
                 for p in client.list_resource('v1', 'Pod', 'default', sel)]
        assert names == ['red']
        sel = {'matchExpressions': [{'key': 'color',
                                     'operator': 'DoesNotExist'}]}
        names = [p['metadata']['name']
                 for p in client.list_resource('v1', 'Pod', 'default', sel)]
        assert names == ['plain']

    def test_cluster_scoped_namespace_resource(self, client):
        client.create_resource('v1', 'Namespace', '', {
            'apiVersion': 'v1', 'kind': 'Namespace',
            'metadata': {'name': 'team-a', 'labels': {'env': 'prod'}}})
        # the API server stamps kubernetes.io/metadata.name on create
        assert client.get_namespace_labels('team-a') == {
            'env': 'prod', 'kubernetes.io/metadata.name': 'team-a'}
        assert client.get_namespace_labels('ghost') == {}

    def test_group_api_resource(self, client):
        client.create_resource('networking.k8s.io/v1', 'NetworkPolicy',
                               'default', {
                                   'apiVersion': 'networking.k8s.io/v1',
                                   'kind': 'NetworkPolicy',
                                   'metadata': {'name': 'deny',
                                                'namespace': 'default'},
                                   'spec': {'podSelector': {}}})
        got = client.get_resource('networking.k8s.io/v1', 'NetworkPolicy',
                                  'default', 'deny')
        assert got['spec'] == {'podSelector': {}}


class TestAccessReview:
    def test_access_review_default_allow(self, client):
        status = client.create_access_review(
            {'verb': 'create', 'group': '', 'resource': 'pods',
             'namespace': 'default', 'subresource': ''})
        assert status.get('allowed') is True

    def test_access_review_denied_over_http(self):
        with FakeApiServer() as srv:
            srv.store.access_review_hook = \
                lambda attrs: (attrs['verb'] != 'delete', 'rbac says no')
            c = HTTPClient(ClusterConfig(server=srv.url))
            try:
                ok = c.create_access_review(
                    {'verb': 'delete', 'group': '', 'resource': 'pods',
                     'namespace': '', 'subresource': ''})
                assert ok.get('allowed') is False
                assert ok.get('reason') == 'rbac says no'
                assert c.create_access_review(
                    {'verb': 'get', 'group': '', 'resource': 'pods',
                     'namespace': '', 'subresource': ''}).get('allowed')
            finally:
                c.close()


class TestHttpOnly:
    """Transport behaviors with no in-memory analogue."""

    def test_json_patch(self):
        with FakeApiServer() as srv:
            c = HTTPClient(ClusterConfig(server=srv.url))
            c.create_resource('v1', 'Pod', 'default', pod('a'))
            out = c.patch_resource('v1', 'Pod', 'default', 'a', [
                {'op': 'add', 'path': '/metadata/labels',
                 'value': {'patched': 'yes'}}])
            assert out['metadata']['labels'] == {'patched': 'yes'}
            c.close()

    def test_watch_streams_events(self):
        with FakeApiServer() as srv:
            c = HTTPClient(ClusterConfig(server=srv.url))
            got = []
            ev = threading.Event()

            def on_event(t, obj):
                got.append((t, obj.get('metadata', {}).get('name')))
                ev.set()
            c.watch(on_event, 'v1', 'Pod', 'default')
            time.sleep(0.3)  # let the watch connect
            srv.store.create_resource('v1', 'Pod', 'default', pod('w1'))
            assert ev.wait(5.0), 'no watch event arrived'
            assert ('ADDED', 'w1') in got
            c.close()

    def test_discovery_resolves_plurals(self):
        with FakeApiServer() as srv:
            c = HTTPClient(ClusterConfig(server=srv.url))
            plural, namespaced = c._resource_info('networking.k8s.io/v1',
                                                  'NetworkPolicy')
            assert plural == 'networkpolicies' and namespaced
            plural, namespaced = c._resource_info('v1', 'Namespace')
            assert plural == 'namespaces' and not namespaced
            c.close()

    def test_raw_abs_path(self):
        with FakeApiServer() as srv:
            c = HTTPClient(ClusterConfig(server=srv.url))
            srv.store.create_resource('v1', 'Pod', 'default', pod('a'))
            raw = c.raw_abs_path('/api/v1/namespaces/default/pods/a')
            import json as _json
            assert _json.loads(raw)['metadata']['name'] == 'a'
            c.close()

    def test_kubeconfig_loading(self, tmp_path):
        import base64
        import yaml
        ca = b'-----BEGIN CERTIFICATE-----\nZZZ\n-----END CERTIFICATE-----'
        cfg = {
            'current-context': 'test',
            'contexts': [{'name': 'test',
                          'context': {'cluster': 'c1', 'user': 'u1'}}],
            'clusters': [{'name': 'c1', 'cluster': {
                'server': 'https://1.2.3.4:6443',
                'certificate-authority-data':
                    base64.b64encode(ca).decode()}}],
            'users': [{'name': 'u1', 'user': {'token': 'sekrit'}}],
        }
        p = tmp_path / 'kubeconfig'
        p.write_text(yaml.safe_dump(cfg))
        conf = load_kubeconfig(str(p))
        assert conf.server == 'https://1.2.3.4:6443'
        assert conf.ca_data == ca
        assert conf.token == 'sekrit'

    def test_status_error_mapping(self):
        from kyverno_tpu.dclient.http_client import error_from_status
        import json as _json
        e = error_from_status(409, _json.dumps(
            {'reason': 'AlreadyExists', 'message': 'dup'}).encode())
        assert isinstance(e, AlreadyExistsError)
        e = error_from_status(409, _json.dumps(
            {'reason': 'Conflict', 'message': 'stale'}).encode())
        assert isinstance(e, ConflictError)
        e = error_from_status(404, b'not json')
        assert isinstance(e, NotFoundError)
