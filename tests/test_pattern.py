import pytest

from kyverno_tpu.engine import pattern
from kyverno_tpu.engine.validate_pattern import match_pattern, PatternError


class TestLeafPattern:
    def test_bool(self):
        assert pattern.validate(True, True)
        assert not pattern.validate(False, True)
        assert not pattern.validate('true', True)

    def test_int(self):
        assert pattern.validate(5, 5)
        assert pattern.validate(5.0, 5)
        assert not pattern.validate(5.5, 5)
        assert pattern.validate('5', 5)
        assert not pattern.validate('x', 5)
        assert not pattern.validate(True, 5)

    def test_float(self):
        assert pattern.validate(5.5, 5.5)
        assert pattern.validate(5, 5.0)
        assert not pattern.validate(5, 5.5)
        assert pattern.validate('5.5', 5.5)

    def test_nil(self):
        assert pattern.validate(None, None)
        assert pattern.validate(0, None)
        assert pattern.validate('', None)
        assert pattern.validate(False, None)
        assert not pattern.validate('x', None)
        assert not pattern.validate({}, None)

    def test_map_existence_only(self):
        assert pattern.validate({'a': 1}, {'x': 99})
        assert not pattern.validate('notmap', {'x': 99})

    def test_string_equal_and_wildcard(self):
        assert pattern.validate('nginx', 'nginx')
        assert pattern.validate('nginx:1.2', 'nginx:*')
        assert not pattern.validate('alpine', 'nginx*')

    def test_string_or(self):
        assert pattern.validate('a', 'a | b')
        assert pattern.validate('b', 'a | b')
        assert not pattern.validate('c', 'a | b')

    def test_string_and(self):
        assert pattern.validate('5', '>1 & <10')
        assert not pattern.validate('11', '>1 & <10')

    def test_numeric_operators(self):
        assert pattern.validate(8080, '>1024')
        assert not pattern.validate(80, '>1024')
        assert pattern.validate(10, '>=10')
        assert pattern.validate(10, '<=10')
        assert pattern.validate(9, '<10')
        assert pattern.validate('512', '!1024')

    def test_quantity_compare(self):
        assert pattern.validate('100Mi', '<1Gi')
        assert pattern.validate('2Gi', '>1G')
        assert pattern.validate('1024Mi', '1Gi')
        assert pattern.validate('100m', '<1')

    def test_duration_compare(self):
        assert pattern.validate('30s', '<1m')
        assert pattern.validate('2h', '>30m')

    def test_range(self):
        assert pattern.validate(5, '1-10')
        assert not pattern.validate(11, '1-10')
        assert pattern.validate(11, '1!-10')
        assert not pattern.validate(5, '1!-10')
        assert pattern.validate('512Mi', '128Mi-1Gi')

    def test_negation(self):
        assert pattern.validate('b', '!a')
        assert not pattern.validate('a', '!a')
        assert not pattern.validate('nginx:latest', '!nginx:*')


class TestMatchPattern:
    def test_simple_match(self):
        resource = {'spec': {'replicas': 3}}
        match_pattern(resource, {'spec': {'replicas': '>1'}})

    def test_simple_fail(self):
        with pytest.raises(PatternError) as ei:
            match_pattern({'spec': {'replicas': 1}}, {'spec': {'replicas': '>1'}})
        assert not ei.value.skip

    def test_missing_key_fails(self):
        with pytest.raises(PatternError):
            match_pattern({'spec': {}}, {'spec': {'replicas': '>1'}})

    def test_star_requires_presence(self):
        match_pattern({'metadata': {'labels': {'app': 'x'}}},
                      {'metadata': {'labels': '*'}})
        with pytest.raises(PatternError):
            match_pattern({'metadata': {}}, {'metadata': {'labels': '*'}})

    def test_array_of_maps(self):
        resource = {'spec': {'containers': [
            {'name': 'a', 'image': 'nginx:1'},
            {'name': 'b', 'image': 'nginx:2'},
        ]}}
        match_pattern(resource, {'spec': {'containers': [{'image': 'nginx:*'}]}})
        with pytest.raises(PatternError):
            match_pattern(resource, {'spec': {'containers': [{'image': 'alpine:*'}]}})

    def test_conditional_anchor_applies(self):
        # if image is nginx:* then tag must not be latest
        pat = {'spec': {'containers': [{'(image)': 'nginx:*', 'imagePullPolicy': 'Always'}]}}
        ok = {'spec': {'containers': [{'image': 'nginx:1', 'imagePullPolicy': 'Always'}]}}
        match_pattern(ok, pat)
        bad = {'spec': {'containers': [{'image': 'nginx:1', 'imagePullPolicy': 'Never'}]}}
        with pytest.raises(PatternError) as ei:
            match_pattern(bad, pat)
        assert not ei.value.skip

    def test_conditional_anchor_skips(self):
        pat = {'spec': {'(hostNetwork)': True, 'replicas': '>100'}}
        # hostNetwork absent -> conditional anchor miss -> skip
        with pytest.raises(PatternError) as ei:
            match_pattern({'spec': {'replicas': 1}}, pat)
        assert ei.value.skip

    def test_conditional_anchor_value_mismatch_skips(self):
        # anchor value doesn't match -> rule skipped
        pat = {'spec': {'containers': [{'(image)': 'nginx:*', 'imagePullPolicy': 'Always'}]}}
        res = {'spec': {'containers': [{'image': 'alpine', 'imagePullPolicy': 'Never'}]}}
        with pytest.raises(PatternError) as ei:
            match_pattern(res, pat)
        assert ei.value.skip

    def test_equality_anchor(self):
        # =(key): if present must match, missing is fine
        pat = {'metadata': {'=(annotations)': {'owner': '?*'}}}
        match_pattern({'metadata': {}}, pat)
        match_pattern({'metadata': {'annotations': {'owner': 'me'}}}, pat)
        with pytest.raises(PatternError) as ei:
            match_pattern({'metadata': {'annotations': {'owner': ''}}}, pat)
        assert not ei.value.skip

    def test_negation_anchor(self):
        pat = {'spec': {'X(hostNetwork)': 'null'}}
        match_pattern({'spec': {}}, pat)
        with pytest.raises(PatternError) as ei:
            match_pattern({'spec': {'hostNetwork': True}}, pat)
        assert not ei.value.skip

    def test_existence_anchor(self):
        pat = {'spec': {'^(containers)': [{'name': 'istio-proxy'}]}}
        match_pattern({'spec': {'containers': [{'name': 'app'}, {'name': 'istio-proxy'}]}}, pat)
        with pytest.raises(PatternError):
            match_pattern({'spec': {'containers': [{'name': 'app'}]}}, pat)

    def test_scalar_array_pattern(self):
        # each element of the resource list must match the scalar pattern
        match_pattern({'ports': [80, 443]}, {'ports': [('>0')]})

    def test_metadata_wildcard_expansion(self):
        pat = {'metadata': {'labels': {'app.kubernetes.io/*': '?*'}}}
        match_pattern({'metadata': {'labels': {'app.kubernetes.io/name': 'x'}}}, pat)

    def test_type_mismatch(self):
        with pytest.raises(PatternError):
            match_pattern({'spec': 'str'}, {'spec': {'a': 1}})
