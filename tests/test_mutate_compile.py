"""Precompiled bulk-mutation appliers must be bit-identical to the
engine loop (statuses, messages, patched docs, UR specs) — VERDICT r4
#4's exactness requirement."""

import random

import pytest

import bench
from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.apply import BatchApplier


@pytest.fixture(scope='module')
def policies():
    return load_policies_from_yaml(bench.CONFIG5_PACK)


def _run(policies, resources, fast, monkey):
    monkey.setenv('KTPU_FAST_MUTATE', '1' if fast else '0')
    applier = BatchApplier(policies, processes=0)
    if fast:
        assert applier._fast_mutate, 'config5 pack should fast-compile'
    return applier.apply(resources, parallel=False)


def test_config5_pack_compiles_fast(policies, monkeypatch):
    monkeypatch.setenv('KTPU_FAST_MUTATE', '1')
    applier = BatchApplier(policies, processes=0)
    # all three mutate policies of the config-5 pack take the fast path
    assert len(applier._fast_mutate) == 3


def test_fast_matches_engine_bit_identical(policies, monkeypatch):
    rng = random.Random(23)
    resources = [bench.make_config5_resource(rng, i) for i in range(400)]
    # shape escapes: labels as non-dict, containers missing
    resources.append({'apiVersion': 'v1', 'kind': 'Pod',
                      'metadata': {'name': 'weird', 'namespace': 'x',
                                   'labels': 'not-a-dict'},
                      'spec': {}})
    resources.append({'apiVersion': 'v1', 'kind': 'Pod',
                      'metadata': {'name': 'already',
                                   'namespace': 'x',
                                   'labels': {'managed': 'true',
                                              'costcenter': 'c9'},
                                   'annotations': {
                                       'policy.io/revision': 'r1'}},
                      'spec': {'containers': [
                          {'name': 'c', 'image': 'i',
                           'imagePullPolicy': 'Always'}]}})
    fast = _run(policies, resources, True, monkeypatch)
    slow = _run(policies, resources, False, monkeypatch)
    assert len(fast) == len(slow)
    for i, (f, s) in enumerate(zip(fast, slow)):
        assert f.rule_results == s.rule_results, (
            i, resources[i]['metadata']['name'],
            f.rule_results, s.rule_results)
        assert f.patched == s.patched, (
            i, resources[i]['metadata']['name'])
        assert f.ur_specs == s.ur_specs


def test_fast_rate_improvement(policies, monkeypatch):
    import time
    rng = random.Random(7)
    resources = [bench.make_config5_resource(rng, i) for i in range(1500)]
    monkeypatch.setenv('KTPU_FAST_MUTATE', '1')
    applier = BatchApplier(policies, processes=0)
    applier.apply(resources[:32], parallel=False)
    t0 = time.time()
    applier.apply(resources, parallel=False)
    fast_s = time.time() - t0
    monkeypatch.setenv('KTPU_FAST_MUTATE', '0')
    slow_applier = BatchApplier(policies, processes=0)
    slow_applier.apply(resources[:32], parallel=False)
    t0 = time.time()
    slow_applier.apply(resources, parallel=False)
    slow_s = time.time() - t0
    # the precompiled path must be dramatically faster on this pack
    assert fast_s * 3 < slow_s, (fast_s, slow_s)


# ---------------------------------------------------------------------------
# fast-path escape hatches: shapes where the engine's semantics diverge
# from the compiled applier must FALLBACK (and stay bit-identical)

def test_json6902_replace_on_missing_path_falls_back():
    """`replace` must FALLBACK when the leaf or any intermediate is
    absent — the engine FAILs with 'replace path not found'; only `add`
    may create paths.  The old fast path silently PASSed and mutated."""
    import json as _json
    from kyverno_tpu.compiler.mutate_compile import (FALLBACK,
                                                     compile_json6902)
    from kyverno_tpu.engine.api import RuleStatus
    patch = _json.dumps([{'op': 'replace',
                          'path': '/metadata/labels/app',
                          'value': 'patched'}])
    fast = compile_json6902(patch)
    assert fast is not None
    # leaf absent
    assert fast.apply({'metadata': {'labels': {}}}) is FALLBACK
    # intermediate absent
    assert fast.apply({'metadata': {}}) is FALLBACK
    assert fast.apply({}) is FALLBACK
    # present: replaces in place, engine-identical
    status, _msg, changed, patched = fast.apply(
        {'metadata': {'labels': {'app': 'old'}}})
    assert status == RuleStatus.PASS and changed
    assert patched['metadata']['labels']['app'] == 'patched'
    # the engine really does FAIL on the shapes we defer
    from kyverno_tpu.engine.mutate.mutate import _apply_json6902
    resp = _apply_json6902(patch, {'metadata': {}})
    assert resp.status == RuleStatus.FAIL
    assert 'not found' in resp.message


def test_json6902_add_still_creates_paths():
    import json as _json
    from kyverno_tpu.compiler.mutate_compile import compile_json6902
    from kyverno_tpu.engine.api import RuleStatus
    patch = _json.dumps([{'op': 'add', 'path': '/metadata/labels/app',
                          'value': 'x'}])
    fast = compile_json6902(patch)
    status, _msg, changed, patched = fast.apply({'metadata': {}})
    assert status == RuleStatus.PASS and changed
    assert patched['metadata']['labels']['app'] == 'x'


def test_foreach_duplicate_element_names_fall_back():
    """Strategic merge coalesces duplicate-named list elements onto the
    first occurrence; the fast path patches independently, so duplicate
    names must take the engine path."""
    from kyverno_tpu.compiler.mutate_compile import (FALLBACK,
                                                     compile_foreach)
    rule = {'name': 'set-pull-policy', 'mutate': {'foreach': [
        {'list': 'request.object.spec.containers',
         'patchStrategicMerge': {'spec': {'containers': [
             {'name': '{{element.name}}',
              'imagePullPolicy': 'IfNotPresent'}]}}}]}}
    fast = compile_foreach(rule['mutate']['foreach'], rule)
    assert fast is not None

    def doc(names):
        return {'apiVersion': 'v1', 'kind': 'Pod',
                'metadata': {'name': 'p', 'namespace': 'd'},
                'spec': {'containers': [
                    {'name': n, 'image': 'i'} for n in names]}}
    assert fast.apply(doc(['a', 'a'])) is FALLBACK
    assert fast.apply(doc(['a', None])) is FALLBACK  # non-string name
    out = fast.apply(doc(['a', 'b']))
    assert out is not FALLBACK
    _status, _msg, changed, patched = out
    assert changed
    assert all(c['imagePullPolicy'] == 'IfNotPresent'
               for c in patched['spec']['containers'])
