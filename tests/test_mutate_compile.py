"""Precompiled bulk-mutation appliers must be bit-identical to the
engine loop (statuses, messages, patched docs, UR specs) — VERDICT r4
#4's exactness requirement."""

import random

import pytest

import bench
from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.apply import BatchApplier


@pytest.fixture(scope='module')
def policies():
    return load_policies_from_yaml(bench.CONFIG5_PACK)


def _run(policies, resources, fast, monkey):
    monkey.setenv('KTPU_FAST_MUTATE', '1' if fast else '0')
    applier = BatchApplier(policies, processes=0)
    if fast:
        assert applier._fast_mutate, 'config5 pack should fast-compile'
    return applier.apply(resources, parallel=False)


def test_config5_pack_compiles_fast(policies, monkeypatch):
    monkeypatch.setenv('KTPU_FAST_MUTATE', '1')
    applier = BatchApplier(policies, processes=0)
    # all three mutate policies of the config-5 pack take the fast path
    assert len(applier._fast_mutate) == 3


def test_fast_matches_engine_bit_identical(policies, monkeypatch):
    rng = random.Random(23)
    resources = [bench.make_config5_resource(rng, i) for i in range(400)]
    # shape escapes: labels as non-dict, containers missing
    resources.append({'apiVersion': 'v1', 'kind': 'Pod',
                      'metadata': {'name': 'weird', 'namespace': 'x',
                                   'labels': 'not-a-dict'},
                      'spec': {}})
    resources.append({'apiVersion': 'v1', 'kind': 'Pod',
                      'metadata': {'name': 'already',
                                   'namespace': 'x',
                                   'labels': {'managed': 'true',
                                              'costcenter': 'c9'},
                                   'annotations': {
                                       'policy.io/revision': 'r1'}},
                      'spec': {'containers': [
                          {'name': 'c', 'image': 'i',
                           'imagePullPolicy': 'Always'}]}})
    fast = _run(policies, resources, True, monkeypatch)
    slow = _run(policies, resources, False, monkeypatch)
    assert len(fast) == len(slow)
    for i, (f, s) in enumerate(zip(fast, slow)):
        assert f.rule_results == s.rule_results, (
            i, resources[i]['metadata']['name'],
            f.rule_results, s.rule_results)
        assert f.patched == s.patched, (
            i, resources[i]['metadata']['name'])
        assert f.ur_specs == s.ur_specs


def test_fast_rate_improvement(policies, monkeypatch):
    import time
    rng = random.Random(7)
    resources = [bench.make_config5_resource(rng, i) for i in range(1500)]
    monkeypatch.setenv('KTPU_FAST_MUTATE', '1')
    applier = BatchApplier(policies, processes=0)
    applier.apply(resources[:32], parallel=False)
    t0 = time.time()
    applier.apply(resources, parallel=False)
    fast_s = time.time() - t0
    monkeypatch.setenv('KTPU_FAST_MUTATE', '0')
    slow_applier = BatchApplier(policies, processes=0)
    slow_applier.apply(resources[:32], parallel=False)
    t0 = time.time()
    slow_applier.apply(resources, parallel=False)
    slow_s = time.time() - t0
    # the precompiled path must be dramatically faster on this pack
    assert fast_s * 3 < slow_s, (fast_s, slow_s)
