"""Metric-name drift gate: every registry write site must use a name
cataloged in observability/catalog.py (scripts/check_metric_names.py)."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    'scripts'))

import check_metric_names  # noqa: E402


def test_all_metric_names_cataloged(capsys):
    rc = check_metric_names.main()
    assert rc == 0, capsys.readouterr().err


def test_catalog_entries_well_formed():
    from kyverno_tpu.observability.catalog import METRICS
    assert METRICS, 'catalog must not be empty'
    for name, metric in METRICS.items():
        assert name.startswith('kyverno'), name
        assert metric.type in ('counter', 'gauge', 'histogram'), name
        assert metric.help.strip(), name
        # prometheus conventions: counters end in _total
        if metric.type == 'counter':
            assert name.endswith('_total'), name


def test_checker_catches_unknown_name(tmp_path, monkeypatch):
    """A call site using an uncataloged literal must fail the check."""
    rogue = os.path.join(check_metric_names.PACKAGE, '_rogue_metric.py')
    with open(rogue, 'w') as f:
        f.write("def emit(reg):\n"
                "    reg.inc('kyverno_tpu_not_in_catalog_total')\n")
    try:
        resolved, _unresolved = check_metric_names.collect_call_sites()
        names = {n for _p, _l, n in resolved}
        assert 'kyverno_tpu_not_in_catalog_total' in names
        catalog = check_metric_names.load_catalog()
        assert 'kyverno_tpu_not_in_catalog_total' not in catalog
    finally:
        os.unlink(rogue)
