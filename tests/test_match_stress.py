"""Adversarial match-path coverage: per-resource labels + selector-based
match rules that defeat the (kind, namespace) group cache
(VERDICT r2 weak #7 — heterogeneous metadata must not collapse
throughput to a per-resource × per-rule Python loop)."""

import random

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

SELECTOR_PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: selector-tier
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: web-pods-need-team
      match:
        any:
          - resources:
              kinds: [Pod]
              selector:
                matchLabels: {tier: web}
      validate:
        message: "web pods need a team label"
        pattern:
          metadata:
            labels:
              team: "?*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: selector-expressions
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: env-in-set
      match:
        any:
          - resources:
              kinds: [Pod]
              selector:
                matchExpressions:
                  - {key: env, operator: In, values: [prod, staging]}
      validate:
        message: "prod/staging pods need requests"
        pattern:
          spec:
            containers:
              - resources:
                  requests:
                    memory: "?*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: name-based
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: named-pods
      match:
        any:
          - resources:
              kinds: [Pod]
              names: ["special-*"]
      validate:
        message: "special pods need app"
        pattern:
          metadata:
            labels:
              app: "?*"
"""


def load_pack():
    return [Policy(d) for d in yaml.safe_load_all(SELECTOR_PACK) if d]


def make_pod(rng, i):
    labels = {}
    if rng.random() < 0.7:
        labels['tier'] = rng.choice(['web', 'db', 'cache'])
    if rng.random() < 0.6:
        labels['env'] = rng.choice(['prod', 'staging', 'dev'])
    if rng.random() < 0.5:
        labels['team'] = rng.choice(['a', 'b'])
    spec = {'containers': [{'name': 'c', 'image': 'nginx:1'}]}
    if rng.random() < 0.5:
        spec['containers'][0]['resources'] = {
            'requests': {'memory': '64Mi'}}
    name = f'special-{i}' if rng.random() < 0.1 else f'pod-{i}'
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': f'ns-{i % 5}',
                         'labels': labels},
            'spec': spec}


class TestSelectorMatch:
    def test_label_tier_classified(self):
        scanner = BatchScanner(load_pack())
        by_rule = {p.rule_name: k for k, p in
                   enumerate(scanner.cps.programs)}
        assert scanner._label_match[by_rule['web-pods-need-team']]
        assert scanner._label_match[by_rule['env-in-set']]
        # name-based match cannot cache on labels
        assert not scanner._label_match[by_rule['named-pods']]
        assert not scanner._simple_match[by_rule['named-pods']]

    def test_device_vs_host_with_selectors(self):
        policies = load_pack()
        engine = Engine()
        rng = random.Random(5)
        resources = [make_pod(rng, i) for i in range(150)]
        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)
        for doc, responses in zip(resources, scanned):
            got = {}
            for er in responses:
                if er.policy_response.rules:
                    got[er.policy_response.policy_name] = {
                        r.name: (r.status, r.message)
                        for r in er.policy_response.rules}
            host = {}
            for pol in policies:
                hr = engine.apply_background_checks(
                    PolicyContext(pol, new_resource=doc))
                if hr.policy_response.rules:
                    host[pol.name] = {r.name: (r.status, r.message)
                                      for r in hr.policy_response.rules}
            assert got == host, f'divergence on {doc["metadata"]}'

    def test_label_cache_scales_with_label_sets_not_resources(self):
        """Selector rules must evaluate once per distinct (group, labels)
        combination — NOT once per resource."""
        policies = load_pack()
        rng = random.Random(6)
        resources = [make_pod(rng, i) for i in range(2000)]
        # force identical names so only labels vary the selector tier
        for doc in resources:
            doc['metadata']['name'] = 'pod-x'
        scanner = BatchScanner(policies)
        calls = [0]
        inner = scanner._match_one

        def counting(j, res, adm=None):
            calls[0] += 1
            return inner(j, res, adm)
        scanner._match_one = counting
        wrapped = [__import__(
            'kyverno_tpu.api.unstructured',
            fromlist=['Resource']).Resource(r) for r in resources]
        scanner.match_matrix(resources, wrapped)
        distinct = len({(doc['metadata']['namespace'],
                         tuple(sorted((doc['metadata'].get('labels') or
                                       {}).items())))
                        for doc in resources})
        label_rules = sum(scanner._label_match)
        # label-tier calls bounded by distinct sets × rules; only the
        # name-based rule runs per resource
        assert calls[0] <= distinct * label_rules + len(resources) + 64, \
            f'{calls[0]} match calls for {distinct} distinct label sets'
