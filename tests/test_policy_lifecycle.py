"""Policy lifecycle: controller UR spawning + admission validation
(reference: pkg/policy/policy_controller.go, pkg/policy/validate.go)."""

import pytest
import yaml

from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.policy.controller import PolicyController
from kyverno_tpu.policy.validate import (PolicyValidationError,
                                         validate_policy)

GENERATE_EXISTING = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: add-quota
spec:
  generateExisting: true
  rules:
    - name: generate-quota
      match: {any: [{resources: {kinds: [Namespace]}}]}
      generate:
        apiVersion: v1
        kind: ResourceQuota
        name: default-quota
        namespace: "{{request.object.metadata.name}}"
        data:
          spec: {hard: {pods: '10'}}
""")

MUTATE_EXISTING = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: label-existing
spec:
  mutateExistingOnPolicyUpdate: true
  rules:
    - name: label-them
      match: {any: [{resources: {kinds: [ConfigMap]}}]}
      mutate:
        targets:
          - apiVersion: v1
            kind: ConfigMap
            namespace: default
        patchStrategicMerge:
          metadata:
            labels:
              seen: "yes"
""")


def make_client():
    client = FakeClient()
    client.create_resource('v1', 'Namespace', '', {
        'apiVersion': 'v1', 'kind': 'Namespace',
        'metadata': {'name': 'team-a'}})
    client.create_resource('v1', 'ConfigMap', 'default', {
        'apiVersion': 'v1', 'kind': 'ConfigMap',
        'metadata': {'name': 'cm1', 'namespace': 'default'}})
    return client


class TestPolicyController:
    def test_generate_existing_spawns_urs(self):
        client = make_client()
        ctrl = PolicyController(client)
        ctrl.add_policy(GENERATE_EXISTING)
        urs = client.list_resource('kyverno.io/v1beta1', 'UpdateRequest',
                                   'kyverno', None)
        assert len(urs) == 1
        spec = urs[0]['spec']
        assert spec['requestType'] == 'generate'
        assert spec['resource']['kind'] == 'Namespace'
        assert spec['resource']['name'] == 'team-a'

    def test_no_urs_without_generate_existing(self):
        client = make_client()
        doc = dict(GENERATE_EXISTING)
        doc['spec'] = dict(doc['spec'])
        doc['spec'].pop('generateExisting')
        ctrl = PolicyController(client)
        ctrl.add_policy(doc)
        urs = client.list_resource('kyverno.io/v1beta1', 'UpdateRequest',
                                   'kyverno', None)
        assert urs == []

    def test_mutate_existing_spawns_urs(self):
        client = make_client()
        ctrl = PolicyController(client)
        ctrl.add_policy(MUTATE_EXISTING)
        urs = client.list_resource('kyverno.io/v1beta1', 'UpdateRequest',
                                   'kyverno', None)
        assert len(urs) == 1
        assert urs[0]['spec']['requestType'] == 'mutate'

    def test_update_only_on_spec_change(self):
        client = make_client()
        ctrl = PolicyController(client)
        ctrl.add_policy(GENERATE_EXISTING)
        before = len(client.list_resource(
            'kyverno.io/v1beta1', 'UpdateRequest', 'kyverno', None))
        # metadata-only change: no new URs
        changed = dict(GENERATE_EXISTING)
        ctrl.update_policy(GENERATE_EXISTING, changed)
        after = len(client.list_resource(
            'kyverno.io/v1beta1', 'UpdateRequest', 'kyverno', None))
        assert after == before


class TestPolicyValidation:
    def base(self):
        return yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: p}
spec:
  rules:
    - name: r1
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        pattern: {metadata: {name: "?*"}}
""")

    def test_accepts_valid(self):
        assert validate_policy(self.base()) == []

    def test_duplicate_rule_names(self):
        doc = self.base()
        doc['spec']['rules'].append(dict(doc['spec']['rules'][0]))
        with pytest.raises(PolicyValidationError, match='duplicate'):
            validate_policy(doc)

    def test_multiple_rule_types(self):
        doc = self.base()
        doc['spec']['rules'][0]['mutate'] = {
            'patchStrategicMerge': {'metadata': {}}}
        with pytest.raises(PolicyValidationError, match='exactly one'):
            validate_policy(doc)

    def test_any_all_conflict(self):
        doc = self.base()
        doc['spec']['rules'][0]['match'] = {
            'any': [{'resources': {'kinds': ['Pod']}}],
            'all': [{'resources': {'kinds': ['Pod']}}]}
        with pytest.raises(PolicyValidationError, match='together'):
            validate_policy(doc)

    def test_invalid_condition_operator(self):
        doc = self.base()
        doc['spec']['rules'][0]['preconditions'] = {
            'all': [{'key': 'x', 'operator': 'Matches', 'value': 'y'}]}
        with pytest.raises(PolicyValidationError, match='invalid operator'):
            validate_policy(doc)

    def test_json_patch_slash(self):
        doc = self.base()
        doc['spec']['rules'][0].pop('validate')
        doc['spec']['rules'][0]['mutate'] = {
            'patchesJson6902': '- {op: add, path: "x/y", value: 1}'}
        with pytest.raises(PolicyValidationError, match='forward slash'):
            validate_policy(doc)

    def test_background_userinfo_rejected(self):
        doc = self.base()
        doc['spec']['rules'][0]['validate']['message'] = \
            'user {{request.userInfo.username}} denied'
        with pytest.raises(PolicyValidationError, match='is not allowed'):
            validate_policy(doc)

    def test_background_false_allows_userinfo(self):
        doc = self.base()
        doc['spec']['background'] = False
        doc['spec']['rules'][0]['validate']['message'] = \
            'user {{request.userInfo.username}} denied'
        assert validate_policy(doc) == []
