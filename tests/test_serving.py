"""Admission micro-batching scheduler (kyverno_tpu/serving/).

Pins the serving contract: with ``KTPU_SERVING=batch`` every response
is bit-identical to the sync path's, overflow/deadline/failure traffic
sheds to the host engine loop (never an error to the API server), and
shutdown drains pending futures.  CPU-only, tier-1.
"""

import json
import threading
import time

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.config.config import Configuration
from kyverno_tpu.policycache import cache as pcache
from kyverno_tpu.policycache.cache import Cache
from kyverno_tpu.serving import shed as shed_policy
from kyverno_tpu.serving.batcher import AdmissionBatcher
from kyverno_tpu.serving.queue import (QueueFull, RequestQueue, Stopped,
                                       Ticket)
from kyverno_tpu.webhooks.handlers import ResourceHandlers
from kyverno_tpu.webhooks.server import WebhookServer

ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""


def pod(labels, name):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'labels': labels},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


def review_bytes(resource, uid, user_info=None):
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {
            'uid': uid, 'operation': 'CREATE',
            'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
            'namespace': 'default',
            'name': resource['metadata']['name'],
            'object': resource,
            'userInfo': user_info or {'username': 'alice', 'groups': []},
        }}).encode()


@pytest.fixture(scope='module')
def chain():
    """One compiled serving chain for the whole module (the scanner
    compile is the expensive part; every test shares it)."""
    cache = Cache()
    cache.warm_up([Policy(d) for d in yaml.safe_load_all(ENFORCE_POLICY)])
    handlers = ResourceHandlers(cache, configuration=Configuration(),
                                serving_mode='batch')
    server = WebhookServer(handlers, configuration=Configuration())
    enforce = cache.get_policies(pcache.VALIDATE_ENFORCE, 'Pod', 'default')
    assert handlers.wait_device_ready(enforce, timeout=600)
    yield server, handlers
    handlers.shutdown()


@pytest.fixture
def restore_batcher(chain):
    """Let a test swap in a custom batcher; the module batcher comes
    back (and batch mode is restored) afterwards."""
    _server, handlers = chain
    prior = handlers._batcher
    prior_mode = handlers.serving_mode
    yield handlers
    custom = handlers._batcher
    if custom is not None and custom is not prior:
        custom.stop(drain=True)
    handlers._batcher = prior
    handlers.serving_mode = prior_mode


def mixed_requests(n):
    # alternate violating / compliant pods so both verdict paths batch
    return [(f'u{i}', pod({'team': 'infra'} if i % 2 else {}, f'p{i}'))
            for i in range(n)]


def sync_responses(server, handlers, requests):
    prior = handlers.serving_mode
    handlers.serving_mode = 'sync'
    try:
        return {uid: server.handle('/validate/fail', review_bytes(p, uid))
                for uid, p in requests}
    finally:
        handlers.serving_mode = prior


class TestBatchedServing:
    def test_stress_bit_identity_and_occupancy(self, chain):
        """32 client threads: batched responses are byte-identical to
        the sync path's, and coalescing actually happens (mean
        occupancy > 1)."""
        server, handlers = chain
        handlers._get_batcher().reset_stats()
        requests = mixed_requests(32 * 8)
        per_thread = 8
        results = {}
        errors = []
        barrier = threading.Barrier(32)

        def work(tid):
            barrier.wait()
            for uid, p in requests[tid * per_thread:
                                   (tid + 1) * per_thread]:
                try:
                    out, status = server.handle_request(
                        '/validate/fail', review_bytes(p, uid))
                    assert status == 200
                    results[uid] = out
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors
        assert len(results) == len(requests)
        stats = handlers._get_batcher().stats()
        assert stats['requests'] + stats['shed_total'] >= len(requests)
        assert stats['occupancy_mean'] > 1.0, stats
        expected = sync_responses(server, handlers, requests)
        for uid, _p in requests:
            assert results[uid] == expected[uid]

    def test_deadline_flush_under_trickle(self, chain):
        """A lone request must not wait for riders: the window deadline
        flushes a batch of one, bit-identical to sync."""
        server, handlers = chain
        batcher = handlers._get_batcher()
        batcher.reset_stats()
        requests = mixed_requests(5)
        got = {uid: server.handle('/validate/fail', review_bytes(p, uid))
               for uid, p in requests}
        stats = batcher.stats()
        assert stats['dispatches'] >= 5
        assert stats['occupancy_p50'] == 1
        expected = sync_responses(server, handlers, requests)
        for uid, _p in requests:
            assert got[uid] == expected[uid]

    def test_queue_full_sheds_to_host_no_500s(self, restore_batcher,
                                              chain):
        """Overflowing a capacity-2 queue sheds to the host engine loop:
        every response stays HTTP 200 and correct, and the shed ledger
        records queue_full."""
        server, handlers = chain
        handlers._batcher = AdmissionBatcher(
            window_ms=50, queue_cap=2,
            on_success=handlers._batch_scan_ok,
            on_failure=handlers._batch_scan_failed)
        requests = mixed_requests(24)
        statuses = []
        results = {}
        errors = []
        barrier = threading.Barrier(12)

        def work(tid):
            barrier.wait()
            for uid, p in requests[tid * 2:(tid + 1) * 2]:
                try:
                    out, status = server.handle_request(
                        '/validate/fail', review_bytes(p, uid))
                    statuses.append(status)
                    results[uid] = out
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors
        assert statuses == [200] * len(requests)
        sheds = handlers._batcher.sheds.counts()
        assert sheds.get(shed_policy.REASON_QUEUE_FULL, 0) >= 1, sheds
        expected = sync_responses(server, handlers, requests)
        for uid, _p in requests:
            assert results[uid] == expected[uid]

    def test_drain_on_stop_resolves_pending(self, restore_batcher,
                                            chain):
        """shutdown() drains: tickets parked behind a huge window get
        real batched responses, and post-stop requests still serve
        (host loop, shed reason shutdown)."""
        server, handlers = chain
        batcher = AdmissionBatcher(
            window_ms=60_000, queue_cap=64, shed_deadline_ms=30_000,
            on_success=handlers._batch_scan_ok,
            on_failure=handlers._batch_scan_failed)
        handlers._batcher = batcher
        requests = mixed_requests(3)
        results = {}

        def work(uid, p):
            results[uid] = server.handle('/validate/fail',
                                         review_bytes(p, uid))

        threads = [threading.Thread(target=work, args=r)
                   for r in requests]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10
        while batcher.queue.depth() < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert batcher.queue.depth() == 3
        handlers.shutdown()
        for t in threads:
            t.join(30)
        assert len(results) == 3
        stats = batcher.stats()
        assert stats['requests'] == 3 and stats['shed_total'] == 0, stats
        # the stopped batcher sheds new submissions to the host loop
        uid, p = 'u-after-stop', pod({}, 'p-after-stop')
        out, status = server.handle_request('/validate/fail',
                                            review_bytes(p, uid))
        assert status == 200
        assert json.loads(out)['response']['allowed'] is False
        assert batcher.sheds.counts().get(
            shed_policy.REASON_SHUTDOWN, 0) >= 1
        expected = sync_responses(server, handlers, requests)
        for r_uid, _p in requests:
            assert results[r_uid] == expected[r_uid]


class _FakeScanner:
    """Scanner WITHOUT per-row admission support: the batcher must key
    its tickets on (serial, canonical admission tuple) — the residual
    fallback path."""

    def __init__(self, fail=False):
        self.fail = fail
        self.calls = []

    def scan(self, resources, contexts=None, admission=None,
             pctx_factory=None):
        self.calls.append(len(resources))
        if self.fail:
            raise RuntimeError('device gone')
        return [[('row', r['metadata']['name'])] for r in resources]


class _RowAdmScanner(_FakeScanner):
    """Scanner WITH per-row admission support: the batcher keys on the
    serial alone and threads each rider's tuple through ``admissions``."""

    def __init__(self):
        super().__init__()
        from kyverno_tpu.compiler.scan import next_scanner_serial
        self.serial = next_scanner_serial()
        self.supports_row_admissions = True
        self.seen_admissions = []

    def scan(self, resources, contexts=None, admission=None,
             pctx_factory=None, admissions=None, old_resources=None):
        self.seen_admissions.append(admissions)
        return super().scan(resources, contexts, admission,
                            pctx_factory)


def _submit(batcher, scanner, name, policies=('pol',)):
    return batcher.submit(
        resource=pod({}, name), context=None, pctx=None,
        admission=({'userInfo': {'username': 'a'}}, [], {}, 'CREATE'),
        scanner=scanner, policies=list(policies))


class TestBatcherUnit:
    def test_scan_error_quarantines_riders_breaker_neutral(self):
        """A persistently failing dispatch quarantines: every rider is
        bisected down to a solo re-dispatch and sheds ``poison_row``
        (row-attributed — each row failed twice in isolation), and one
        all-failed batch fires NEITHER breaker callback (see
        ALL_FAILED_BREAKER_AFTER for the escalation rule)."""
        failures = []
        batcher = AdmissionBatcher(
            window_ms=60_000, max_batch=3, queue_cap=16,
            on_failure=lambda policies, e: failures.append(str(e)))
        try:
            scanner = _FakeScanner(fail=True)
            tickets = [_submit(batcher, scanner, f'p{i}')
                       for i in range(3)]
            rows = [t.wait(shed_after_s=5.0) for t in tickets]
            assert rows == [None, None, None]
            assert all(t.shed_reason == shed_policy.REASON_POISON_ROW
                       for t in tickets)
            counts = batcher.sheds.counts()
            assert counts.get(shed_policy.REASON_POISON_ROW) == 3
            assert shed_policy.REASON_SCAN_ERROR not in counts
            time.sleep(0.05)  # the (absent) verdict would land late
            assert failures == []
        finally:
            batcher.stop(drain=False)

    def test_occupancy_cap_flushes_full_batch(self):
        batcher = AdmissionBatcher(window_ms=60_000, max_batch=4,
                                   queue_cap=64)
        try:
            scanner = _FakeScanner()
            tickets = [_submit(batcher, scanner, f'p{i}')
                       for i in range(4)]
            rows = [t.wait(shed_after_s=10.0) for t in tickets]
            # the window was huge: only the occupancy cap can have
            # flushed this batch
            assert all(r is not None for r in rows)
            assert scanner.calls == [4]
        finally:
            batcher.stop(drain=False)

    def test_residual_scanner_keeps_per_tuple_isolation(self):
        """A scanner without per-row admission support must never mix
        distinct admission tuples in one dispatch (the residual key
        appends the canonical tuple)."""
        batcher = AdmissionBatcher(window_ms=30, queue_cap=64)
        try:
            scanner = _FakeScanner()
            t1 = batcher.submit(
                resource=pod({}, 'a'), context=None, pctx=None,
                admission=({'userInfo': {'username': 'alice'}}, [], {},
                           'CREATE'),
                scanner=scanner, policies=['pol'])
            t2 = batcher.submit(
                resource=pod({}, 'b'), context=None, pctx=None,
                admission=({'userInfo': {'username': 'bob'}}, [], {},
                           'CREATE'),
                scanner=scanner, policies=['pol'])
            assert t1.wait(5.0) is not None
            assert t2.wait(5.0) is not None
            assert scanner.calls == [1, 1]
        finally:
            batcher.stop(drain=False)

    def test_row_admission_scanner_coalesces_distinct_tuples(self):
        """The tentpole contract: with per-row admission support the
        batch key is the scanner serial alone — distinct users share
        ONE dispatch and each rider's tuple rides as a row."""
        batcher = AdmissionBatcher(window_ms=60_000, max_batch=2,
                                   queue_cap=64)
        try:
            scanner = _RowAdmScanner()
            adm_a = ({'userInfo': {'username': 'alice'}}, [], {},
                     'CREATE')
            adm_b = ({'userInfo': {'username': 'bob'}}, [], {},
                     'UPDATE')
            t1 = batcher.submit(resource=pod({}, 'a'), context=None,
                                pctx=None, admission=adm_a,
                                scanner=scanner, policies=['pol'])
            t2 = batcher.submit(resource=pod({}, 'b'), context=None,
                                pctx=None, admission=adm_b,
                                scanner=scanner, policies=['pol'])
            assert t1.wait(5.0) is not None
            assert t2.wait(5.0) is not None
            # the huge window proves only the occupancy cap (2) could
            # have flushed: both tuples rode one dispatch
            assert scanner.calls == [2]
            assert scanner.seen_admissions == [[adm_a, adm_b]]
            stats = batcher.stats()
            assert stats['hetero_dispatches'] == 1
            assert stats['hetero_occupancy_mean'] == 2.0
        finally:
            batcher.stop(drain=False)

    def test_canonical_admission_key_coalesces_reordered_lists(self):
        """Equivalent tuples differing only in list order produce one
        residual key (deterministic canonicalization)."""
        batcher = AdmissionBatcher(window_ms=60_000, max_batch=2,
                                   queue_cap=64)
        try:
            scanner = _FakeScanner()  # residual path
            base = {'userInfo': {'username': 'u',
                                 'groups': ['a', 'b']}, 'roles': ['r1',
                                                                  'r2']}
            flip = {'userInfo': {'username': 'u',
                                 'groups': ['b', 'a']}, 'roles': ['r2',
                                                                  'r1']}
            t1 = batcher.submit(resource=pod({}, 'a'), context=None,
                                pctx=None,
                                admission=(base, [], {}, 'CREATE'),
                                scanner=scanner, policies=['pol'])
            t2 = batcher.submit(resource=pod({}, 'b'), context=None,
                                pctx=None,
                                admission=(flip, [], {}, 'CREATE'),
                                scanner=scanner, policies=['pol'])
            assert t1.wait(5.0) is not None
            assert t2.wait(5.0) is not None
            assert scanner.calls == [2]
        finally:
            batcher.stop(drain=False)

    def test_deadline_shed_vs_claim_is_exclusive(self):
        sheds = []
        ticket = Ticket(key='k', resource={}, context=None, pctx=None,
                        admission=(), scanner=None, policies=[],
                        on_shed=sheds.append)
        assert ticket.wait(shed_after_s=0.01) is None
        assert ticket.shed_reason == shed_policy.REASON_DEADLINE
        assert sheds == [shed_policy.REASON_DEADLINE]
        # the loser of the CAS cannot claim a shed ticket
        assert not ticket.claim()

    def test_queue_capacity_and_stop(self):
        q = RequestQueue(capacity=2)
        t1 = Ticket('k', {}, None, None, (), None, [])
        t2 = Ticket('k', {}, None, None, (), None, [])
        q.put(t1)
        q.put(t2)
        with pytest.raises(QueueFull):
            q.put(Ticket('k', {}, None, None, (), None, []))
        # a deadline-shed ticket no longer counts against capacity
        assert t1._try_shed(shed_policy.REASON_DEADLINE)
        q.put(Ticket('k', {}, None, None, (), None, []))
        q.stop()
        with pytest.raises(Stopped):
            q.put(Ticket('k', {}, None, None, (), None, []))

    def test_metrics_emission(self):
        from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                                       set_global_registry)
        from kyverno_tpu.serving.batcher import (BATCH_OCCUPANCY,
                                                 QUEUE_WAIT)
        from kyverno_tpu.serving.shed import ADMISSION_SHED
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            batcher = AdmissionBatcher(window_ms=5, queue_cap=8)
            try:
                scanner = _FakeScanner()
                tickets = [_submit(batcher, scanner, f'p{i}')
                           for i in range(2)]
                for t in tickets:
                    assert t.wait(5.0) is not None
                batcher.record_shed(shed_policy.REASON_QUEUE_FULL)
                assert registry.histogram_count(
                    BATCH_OCCUPANCY) >= 1
                assert registry.histogram_count(QUEUE_WAIT) >= 2
                assert registry.counter_value(
                    ADMISSION_SHED,
                    reason=shed_policy.REASON_QUEUE_FULL) == 1
            finally:
                batcher.stop(drain=False)
        finally:
            set_global_registry(None)


# ---------------------------------------------------------------------------
# full-verb batching: UPDATE validate rows and mutate requests ride the
# same queue/coalescing loop (PR 8) — the batch key no longer excludes
# verbs, and the host engine loop stays the bit-identity oracle.

MUTATE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: add-team-label
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: add-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchStrategicMerge:
          metadata:
            labels:
              "+(team)": platform
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: stamp-managed
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: stamp
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchesJson6902: |-
          - op: add
            path: /metadata/annotations/managed
            value: kyverno-tpu
"""

# the selector only matches the OLD object of some UPDATE requests —
# the engine's old-match retry must survive batching
LEGACY_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: legacy-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: legacy-team
      match:
        any:
          - resources:
              kinds: [Pod]
              selector: {matchLabels: {legacy: "yes"}}
      validate:
        message: "legacy pods must be marked migrated"
        pattern:
          metadata:
            labels:
              migrated: "?*"
"""


def update_review_bytes(resource, old_resource, uid):
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {
            'uid': uid, 'operation': 'UPDATE',
            'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
            'namespace': 'default',
            'name': resource['metadata']['name'],
            'object': resource, 'oldObject': old_resource,
            'userInfo': {'username': 'alice', 'groups': []},
        }}).encode()


@pytest.fixture(scope='module')
def verb_chain():
    """Validate (incl. a selector rule exercising the old-match retry)
    + mutate policies on one compiled chain in batch serving mode."""
    docs = list(yaml.safe_load_all(ENFORCE_POLICY)) + \
        list(yaml.safe_load_all(LEGACY_POLICY)) + \
        list(yaml.safe_load_all(MUTATE_POLICY))
    cache = Cache()
    cache.warm_up([Policy(d) for d in docs if d])
    handlers = ResourceHandlers(cache, configuration=Configuration(),
                                serving_mode='batch')
    server = WebhookServer(handlers, configuration=Configuration())
    enforce = cache.get_policies(pcache.VALIDATE_ENFORCE, 'Pod', 'default')
    assert handlers.wait_device_ready(enforce, timeout=600)
    mut = cache.get_policies(pcache.MUTATE, 'Pod', 'default')
    deadline = time.time() + 120
    scanner = None
    while time.time() < deadline:
        scanner = handlers._device_scanner(mut, kind='mutate')
        if scanner is not None:
            break
        time.sleep(0.02)
    assert scanner is not None and scanner.ok
    yield server, handlers
    handlers.shutdown()


def mixed_verb_requests(n):
    """CREATE/UPDATE mixed validate traffic; some UPDATE rows match the
    legacy selector only through their old object."""
    out = []
    for i in range(n):
        labels = {'team': 'infra'} if i % 2 else {}
        new = pod(dict(labels), f'p{i}')
        if i % 3 == 0:
            old = pod({'legacy': 'yes', **labels}, f'p{i}')
            out.append((f'u{i}', 'UPDATE', new, old))
        elif i % 3 == 1:
            out.append((f'u{i}', 'UPDATE', new, pod(dict(labels), f'p{i}')))
        else:
            out.append((f'u{i}', 'CREATE', new, None))
    return out


def _verb_bytes(entry):
    uid, op, new, old = entry
    if op == 'UPDATE':
        return update_review_bytes(new, old, uid)
    return review_bytes(new, uid)


class TestFullVerbBatching:
    def test_mixed_verb_batched_bit_identity(self, verb_chain):
        """16 threads of UPDATE+CREATE validate traffic: batched
        responses byte-identical to sync, coalescing observed."""
        server, handlers = verb_chain
        handlers._get_batcher().reset_stats()
        requests = mixed_verb_requests(16 * 8)
        results = {}
        errors = []
        barrier = threading.Barrier(16)

        def work(tid):
            barrier.wait()
            for entry in requests[tid * 8:(tid + 1) * 8]:
                try:
                    out, status = server.handle_request(
                        '/validate/fail', _verb_bytes(entry))
                    assert status == 200
                    results[entry[0]] = out
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors
        stats = handlers._get_batcher().stats()
        assert stats['occupancy_mean'] > 1.0, stats
        prior = handlers.serving_mode
        handlers.serving_mode = 'sync'
        try:
            expected = {e[0]: server.handle('/validate/fail',
                                            _verb_bytes(e))
                        for e in requests}
        finally:
            handlers.serving_mode = prior
        for entry in requests:
            assert results[entry[0]] == expected[entry[0]]

    def test_update_old_match_retry_identical_to_host(self, verb_chain):
        """An UPDATE whose old object alone matches the legacy selector
        must deny exactly like the pure host engine loop."""
        server, handlers = verb_chain
        # new passes require-team but is not 'migrated'; only the OLD
        # object carries the legacy selector label, so the rule applies
        # to this UPDATE solely through the old-match retry
        new = pod({'team': 'infra'}, 'retry-pod')
        old = pod({'legacy': 'yes', 'team': 'infra'}, 'retry-pod')
        body = update_review_bytes(new, old, 'u-retry')
        batched = server.handle('/validate/fail', body)
        prior_mode, prior_device = handlers.serving_mode, handlers.device
        handlers.serving_mode = 'sync'
        try:
            synced = server.handle('/validate/fail', body)
            handlers.device = False
            host = server.handle('/validate/fail', body)
        finally:
            handlers.serving_mode, handlers.device = \
                prior_mode, prior_device
        assert batched == synced == host
        assert json.loads(batched)['response']['allowed'] is False
        # the same new object on CREATE passes (selector never matches)
        create = json.loads(server.handle(
            '/validate/fail', review_bytes(new, 'u-retry-create')))
        assert create['response']['allowed'] is True

    def test_batched_mutate_byte_identical_to_host_engine(self,
                                                          verb_chain):
        """Mutate responses through the batched device path are
        byte-identical to the host engine loop, and concurrent mutate
        requests coalesce (occupancy > 1)."""
        server, handlers = verb_chain
        handlers._get_batcher().reset_stats()
        requests = []
        for i in range(48):
            labels = {'team': 'x'} if i % 2 else {}
            new = pod(dict(labels), f'm{i}')
            if i % 3 == 0:
                requests.append((f'mu{i}', 'UPDATE', new,
                                 pod(dict(labels), f'm{i}')))
            else:
                requests.append((f'mu{i}', 'CREATE', new, None))
        results = {}
        errors = []
        barrier = threading.Barrier(12)

        def work(tid):
            barrier.wait()
            for entry in requests[tid * 4:(tid + 1) * 4]:
                try:
                    out, status = server.handle_request(
                        '/mutate', _verb_bytes(entry))
                    assert status == 200
                    results[entry[0]] = out
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors
        stats = handlers._get_batcher().stats()
        assert stats['occupancy_mean'] > 1.0, stats
        # oracle: the pure host engine loop (device mutate off)
        prior = handlers.mutate_device
        handlers.mutate_device = False
        try:
            expected = {e[0]: server.handle('/mutate', _verb_bytes(e))
                        for e in requests}
        finally:
            handlers.mutate_device = prior
        for entry in requests:
            assert results[entry[0]] == expected[entry[0]]
        # and patches actually flowed
        sample = json.loads(results['mu1'])
        assert sample['response'].get('patch')

    def test_shed_to_host_never_500_on_new_verb_paths(
            self, restore_batcher, verb_chain):
        """Overflowing a tiny queue with mixed UPDATE validate + mutate
        traffic sheds to the host loop: all 200s, identical bytes."""
        server, handlers = verb_chain
        handlers._batcher = AdmissionBatcher(
            window_ms=50, queue_cap=2,
            on_success=handlers._batch_scan_ok,
            on_failure=handlers._batch_scan_failed)
        requests = mixed_verb_requests(24)
        statuses = []
        results = {}
        errors = []
        barrier = threading.Barrier(12)

        def work(tid):
            barrier.wait()
            for entry in requests[tid * 2:(tid + 1) * 2]:
                route = '/mutate' if int(entry[0][1:]) % 2 else \
                    '/validate/fail'
                try:
                    out, status = server.handle_request(
                        route, _verb_bytes(entry))
                    statuses.append(status)
                    results[(route, entry[0])] = out
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors
        assert statuses == [200] * len(requests)
        prior_mode = handlers.serving_mode
        prior_mut = handlers.mutate_device
        handlers.serving_mode = 'sync'
        handlers.mutate_device = False
        try:
            for (route, uid), got in results.items():
                entry = next(e for e in requests if e[0] == uid)
                assert got == server.handle(route, _verb_bytes(entry))
        finally:
            handlers.serving_mode = prior_mode
            handlers.mutate_device = prior_mut


# ---------------------------------------------------------------------------
# heterogeneous-traffic batching (PR 10): the batch key is the policy
# set alone — N threads with DISTINCT users/groups/roles + mixed verbs
# coalesce into shared dispatches, each response pinned identical to
# that request's own sync scan (and to the pure host engine loop).

ADMIN_GATE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: admins-only-hetero
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: admins-only
      match:
        any:
          - resources: {kinds: [Pod]}
            subjects:
              - {kind: Group, name: system:masters}
              - {kind: User, name: root-user}
      validate:
        message: "admin-gated pods need a ticket label"
        pattern:
          metadata: {labels: {ticket: "?*"}}
"""


@pytest.fixture(scope='module')
def hetero_chain():
    """Plain + subject-gated validate policies on one batch-mode chain:
    the subject rule's match depends on each request's userInfo, so
    correctness under coalescing requires the per-row admission lanes."""
    docs = list(yaml.safe_load_all(ENFORCE_POLICY)) + \
        list(yaml.safe_load_all(ADMIN_GATE_POLICY))
    cache = Cache()
    cache.warm_up([Policy(d) for d in docs if d])
    handlers = ResourceHandlers(cache, configuration=Configuration(),
                                serving_mode='batch')
    server = WebhookServer(handlers, configuration=Configuration())
    enforce = cache.get_policies(pcache.VALIDATE_ENFORCE, 'Pod', 'default')
    assert handlers.wait_device_ready(enforce, timeout=600)
    yield server, handlers
    handlers.shutdown()


def hetero_requests(n):
    """Mixed users (some admins), mixed verbs, mixed verdicts — every
    request carries a DISTINCT admission tuple."""
    out = []
    for i in range(n):
        user = {'username': f'user-{i}',
                'groups': ['system:authenticated'] +
                          (['system:masters'] if i % 4 == 0 else []) +
                          [f'team-{i % 5}']}
        if i % 7 == 0:
            user = {'username': 'root-user', 'groups': [f'team-{i % 5}']}
        labels = {}
        if i % 2:
            labels['team'] = 'infra'
        if i % 3 == 0:
            labels['ticket'] = f'T-{i}'
        new = pod(dict(labels), f'h{i}')
        if i % 5 == 2:
            out.append((f'h{i}', 'UPDATE', new, pod(dict(labels), f'h{i}'),
                        user))
        else:
            out.append((f'h{i}', 'CREATE', new, None, user))
    return out


def _hetero_bytes(entry):
    uid, op, new, old, user = entry
    if op == 'UPDATE':
        body = json.loads(update_review_bytes(new, old, uid))
        body['request']['userInfo'] = user
        return json.dumps(body).encode()
    return review_bytes(new, uid, user_info=user)


class TestHeterogeneousBatching:
    def test_mixed_tuple_bit_identity_and_occupancy(self, hetero_chain):
        """16 threads × distinct users/groups/verbs in one window:
        occupancy > 1 with heterogeneous dispatches observed, every
        response byte-identical to that request's own sync scan AND to
        the pure host engine loop."""
        server, handlers = hetero_chain
        handlers._get_batcher().reset_stats()
        requests = hetero_requests(16 * 8)
        results = {}
        errors = []
        barrier = threading.Barrier(16)

        def work(tid):
            barrier.wait()
            for entry in requests[tid * 8:(tid + 1) * 8]:
                try:
                    out, status = server.handle_request(
                        '/validate/fail', _hetero_bytes(entry))
                    assert status == 200
                    results[entry[0]] = out
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors
        assert len(results) == len(requests)
        stats = handlers._get_batcher().stats()
        # the tentpole: DISTINCT admission tuples coalesced
        assert stats['occupancy_mean'] > 1.0, stats
        assert stats['hetero_dispatches'] >= 1, stats
        # oracle 1: per-request sync scans (same scanner, occupancy 1)
        prior = handlers.serving_mode
        handlers.serving_mode = 'sync'
        try:
            expected = {e[0]: server.handle('/validate/fail',
                                            _hetero_bytes(e))
                        for e in requests}
        finally:
            handlers.serving_mode = prior
        for entry in requests:
            assert results[entry[0]] == expected[entry[0]], entry[0]
        # oracle 2: the pure host engine loop on a verdict-bearing mix
        prior_device = handlers.device
        handlers.device = False
        try:
            for entry in requests[:24]:
                host = server.handle('/validate/fail',
                                     _hetero_bytes(entry))
                assert results[entry[0]] == host, entry[0]
        finally:
            handlers.device = prior_device

    def test_admin_gate_verdicts_depend_on_row_user(self, hetero_chain):
        """Same pod, different users, one batch window: the subject-
        gated rule must deny only the admin-group rows — per-row lanes,
        not the lead rider's tuple, decide each row."""
        server, handlers = hetero_chain
        doc = pod({'team': 'infra'}, 'gate-pod')  # no ticket label
        admin = {'username': 'boss', 'groups': ['system:masters']}
        human = {'username': 'dev-1', 'groups': ['system:authenticated']}
        results = {}
        barrier = threading.Barrier(2)

        def work(uid, user):
            barrier.wait()
            results[uid] = server.handle(
                '/validate/fail', review_bytes(doc, uid, user_info=user))

        threads = [threading.Thread(target=work, args=a)
                   for a in [('adm', admin), ('hum', human)]]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert json.loads(results['adm'])['response']['allowed'] is False
        assert json.loads(results['hum'])['response']['allowed'] is True
