import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.engine.api import PolicyContext, RuleStatus
from kyverno_tpu.engine.engine import Engine


def run(policy_yaml, resource, **kw):
    policy = Policy(yaml.safe_load(policy_yaml))
    pctx = PolicyContext(policy, new_resource=resource, **kw)
    return Engine().validate(pctx)


DISALLOW_LATEST = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: disallow-latest-tag
spec:
  validationFailureAction: Enforce
  rules:
    - name: require-image-tag
      match:
        any:
          - resources:
              kinds: [Pod]
      validate:
        message: "An image tag is required."
        pattern:
          spec:
            containers:
              - image: "!*:latest"
"""


def pod(containers, kind='Pod', name='test-pod', labels=None):
    return {
        'apiVersion': 'v1', 'kind': kind,
        'metadata': {'name': name, 'namespace': 'default',
                     **({'labels': labels} if labels else {})},
        'spec': {'containers': containers},
    }


class TestValidatePattern:
    def test_pass(self):
        resp = run(DISALLOW_LATEST, pod([{'name': 'a', 'image': 'nginx:1.25'}]))
        assert len(resp.policy_response.rules) == 1
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.PASS
        assert r.message == "validation rule 'require-image-tag' passed."

    def test_fail_message_format(self):
        resp = run(DISALLOW_LATEST, pod([{'name': 'a', 'image': 'nginx:latest'}]))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.FAIL
        assert r.message.startswith(
            'validation error: An image tag is required. rule '
            'require-image-tag failed at path')
        assert not resp.is_successful()

    def test_no_match_no_rules(self):
        resp = run(DISALLOW_LATEST, {
            'apiVersion': 'v1', 'kind': 'Service',
            'metadata': {'name': 's', 'namespace': 'default'}, 'spec': {}})
        assert resp.is_empty()


PRECONDITIONS = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: check-replicas
spec:
  rules:
    - name: check-replicas
      match:
        any:
          - resources:
              kinds: [Deployment]
      preconditions:
        all:
          - key: "{{request.object.metadata.labels.critical || ''}}"
            operator: Equals
            value: "true"
      validate:
        message: "critical deployments need >= 2 replicas"
        pattern:
          spec:
            replicas: ">=2"
"""


def deployment(replicas, labels=None):
    return {
        'apiVersion': 'apps/v1', 'kind': 'Deployment',
        'metadata': {'name': 'd', 'namespace': 'default',
                     **({'labels': labels} if labels else {})},
        'spec': {'replicas': replicas,
                 'template': {'metadata': {}, 'spec': {'containers': [
                     {'name': 'c', 'image': 'nginx:1'}]}}},
    }


class TestPreconditions:
    def test_skip_when_not_met(self):
        resp = run(PRECONDITIONS, deployment(1))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.SKIP
        assert r.message == 'preconditions not met'

    def test_applies_when_met(self):
        resp = run(PRECONDITIONS, deployment(1, labels={'critical': 'true'}))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.FAIL
        resp = run(PRECONDITIONS, deployment(3, labels={'critical': 'true'}))
        assert resp.policy_response.rules[0].status == RuleStatus.PASS


DENY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: deny-delete
spec:
  rules:
    - name: block-prod-deletes
      match:
        any:
          - resources:
              kinds: [ConfigMap]
      validate:
        message: "Deleting {{request.object.metadata.name}} is not allowed"
        deny:
          conditions:
            any:
              - key: "{{request.operation}}"
                operator: Equals
                value: DELETE
"""


class TestDeny:
    def test_deny_fail(self):
        # DELETE request: resource arrives as oldObject, newObject is empty
        cm = {'apiVersion': 'v1', 'kind': 'ConfigMap',
              'metadata': {'name': 'cm1', 'namespace': 'default'}}
        policy = Policy(yaml.safe_load(DENY))
        pctx = PolicyContext(policy, old_resource=cm,
                             admission_operation='DELETE')
        resp = Engine().validate(pctx)
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.FAIL
        assert r.message == 'Deleting cm1 is not allowed'

    def test_deny_pass(self):
        cm = {'apiVersion': 'v1', 'kind': 'ConfigMap',
              'metadata': {'name': 'cm1', 'namespace': 'default'}}
        resp = run(DENY, cm, admission_operation='CREATE')
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.PASS
        assert r.message == "validation rule 'block-prod-deletes' passed."


FOREACH = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: check-registries
spec:
  rules:
    - name: check-registry
      match:
        any:
          - resources:
              kinds: [Pod]
      validate:
        message: "unknown registry"
        foreach:
          - list: "request.object.spec.containers"
            deny:
              conditions:
                all:
                  - key: "{{element.image}}"
                    operator: AnyNotIn
                    value:
                      - "ghcr.io/*"
                      - "registry.k8s.io/*"
"""


class TestForeach:
    def test_all_allowed(self):
        resp = run(FOREACH, pod([
            {'name': 'a', 'image': 'ghcr.io/org/app:1'},
            {'name': 'b', 'image': 'registry.k8s.io/pause:3.9'}]))
        assert resp.policy_response.rules[0].status == RuleStatus.PASS

    def test_one_denied(self):
        resp = run(FOREACH, pod([
            {'name': 'a', 'image': 'ghcr.io/org/app:1'},
            {'name': 'b', 'image': 'docker.io/evil:1'}]))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.FAIL
        assert r.message.startswith('validation failure:')


ANY_PATTERN = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-labels
spec:
  rules:
    - name: require-team-label
      match:
        any:
          - resources:
              kinds: [Pod]
      validate:
        message: "team label required"
        anyPattern:
          - metadata:
              labels:
                team: "?*"
          - metadata:
              labels:
                squad: "?*"
"""


class TestAnyPattern:
    def test_first_pattern(self):
        resp = run(ANY_PATTERN, pod([{'name': 'a', 'image': 'x'}],
                                    labels={'team': 'infra'}))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.PASS
        assert 'anyPattern[0] passed' in r.message

    def test_second_pattern(self):
        resp = run(ANY_PATTERN, pod([{'name': 'a', 'image': 'x'}],
                                    labels={'squad': 'infra'}))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.PASS
        assert 'anyPattern[1] passed' in r.message

    def test_none_fail(self):
        resp = run(ANY_PATTERN, pod([{'name': 'a', 'image': 'x'}]))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.FAIL
        assert r.message.startswith('validation error: team label required.')


AUTOGEN = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: disallow-latest
spec:
  rules:
    - name: no-latest
      match:
        any:
          - resources:
              kinds: [Pod]
      validate:
        message: "no latest tag"
        pattern:
          spec:
            containers:
              - image: "!*:latest"
"""


class TestAutogen:
    def test_deployment_autogen_rule_applies(self):
        resp = run(AUTOGEN, deployment(1))
        names = [r.name for r in resp.policy_response.rules]
        assert 'autogen-no-latest' in names

    def test_deployment_autogen_fails_on_latest(self):
        d = deployment(1)
        d['spec']['template']['spec']['containers'][0]['image'] = 'nginx:latest'
        resp = run(AUTOGEN, d)
        statuses = {r.name: r.status for r in resp.policy_response.rules}
        assert statuses['autogen-no-latest'] == RuleStatus.FAIL

    def test_cronjob_autogen(self):
        cj = {
            'apiVersion': 'batch/v1',
            'kind': 'CronJob',
            'metadata': {'name': 'cj', 'namespace': 'default'},
            'spec': {'jobTemplate': {'spec': {'template': {'spec': {
                'containers': [{'name': 'c', 'image': 'job:latest'}],
            }}}}},
        }
        resp = run(AUTOGEN, cj)
        statuses = {r.name: r.status for r in resp.policy_response.rules}
        assert statuses.get('autogen-cronjob-no-latest') == RuleStatus.FAIL


PSS_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: psa
  annotations:
    pod-policies.kyverno.io/autogen-controllers: none
spec:
  rules:
    - name: baseline
      match:
        any:
          - resources:
              kinds: [Pod]
      validate:
        podSecurity:
          level: baseline
          version: latest
"""


class TestPodSecurity:
    def test_baseline_pass(self):
        resp = run(PSS_POLICY, pod([{'name': 'a', 'image': 'nginx:1'}]))
        assert resp.policy_response.rules[0].status == RuleStatus.PASS

    def test_privileged_fails(self):
        resp = run(PSS_POLICY, pod([
            {'name': 'a', 'image': 'nginx:1',
             'securityContext': {'privileged': True}}]))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.FAIL
        assert 'PodSecurity "baseline:latest"' in r.message
        assert 'privileged' in r.message

    def test_exclusion(self):
        policy_yaml = PSS_POLICY.replace(
            'version: latest',
            'version: latest\n          exclude:\n'
            '            - controlName: "Privileged Containers"\n'
            '              images: ["nginx:*"]')
        resp = run(policy_yaml, pod([
            {'name': 'a', 'image': 'nginx:1',
             'securityContext': {'privileged': True}}]))
        assert resp.policy_response.rules[0].status == RuleStatus.PASS


EXCEPTION = {
    'apiVersion': 'kyverno.io/v2alpha1', 'kind': 'PolicyException',
    'metadata': {'name': 'ex-1', 'namespace': 'default'},
    'spec': {
        'exceptions': [{'policyName': 'disallow-latest-tag',
                        'ruleNames': ['require-image-tag']}],
        'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
    },
}


class TestExceptions:
    def test_exception_skips_rule(self):
        resp = run(DISALLOW_LATEST, pod([{'name': 'a', 'image': 'nginx:latest'}]),
                   exceptions=[EXCEPTION])
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.SKIP
        assert 'policy exception' in r.message


class TestNamespacedPolicy:
    def test_namespace_mismatch_skips(self):
        p = yaml.safe_load(DISALLOW_LATEST)
        p['kind'] = 'Policy'
        p['metadata']['namespace'] = 'other'
        policy = Policy(p)
        pctx = PolicyContext(policy, new_resource=pod(
            [{'name': 'a', 'image': 'nginx:latest'}]))
        resp = Engine().validate(pctx)
        assert resp.is_empty()


EXCLUDE_SUBJECTS = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: exclude-admin
spec:
  rules:
    - name: no-latest
      match:
        any:
          - resources:
              kinds: [Pod]
      exclude:
        any:
          - subjects:
              - kind: User
                name: admin
      validate:
        message: "no latest"
        pattern:
          spec:
            containers:
              - image: "!*:latest"
"""


class TestExcludeSemantics:
    def test_exclude_subjects_without_admission_info_does_not_exclude(self):
        # background scan (no admission info): subject exclusion must NOT fire
        resp = run(EXCLUDE_SUBJECTS, pod([{'name': 'a', 'image': 'x:latest'}]))
        assert len(resp.policy_response.rules) == 1
        assert resp.policy_response.rules[0].status == RuleStatus.FAIL

    def test_exclude_subjects_matching_user_excludes(self):
        resp = run(EXCLUDE_SUBJECTS, pod([{'name': 'a', 'image': 'x:latest'}]),
                   admission_info={'userInfo': {'username': 'admin'}})
        assert resp.is_empty()

    def test_empty_match_any_filter_does_not_match(self):
        p = yaml.safe_load(DISALLOW_LATEST)
        p['spec']['rules'][0]['match'] = {'any': [{}]}
        resp = Engine().validate(PolicyContext(
            Policy(p), new_resource=pod([{'name': 'a', 'image': 'x:latest'}])))
        assert resp.is_empty()


UNRESOLVED_VAR = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: bad-var
spec:
  rules:
    - name: check
      match:
        any:
          - resources:
              kinds: [Pod]
      validate:
        message: "x"
        pattern:
          metadata:
            name: "{{request.object.metadata.annotations.team}}"
"""


class TestUnresolvedVariables:
    def test_unresolved_variable_errors_rule(self):
        # pod without the annotation: substitution must ERROR (fork behavior)
        resp = run(UNRESOLVED_VAR, pod([{'name': 'a', 'image': 'x'}]))
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.ERROR
        assert 'variable substitution failed' in r.message
