"""Fault injection, poison quarantine, breaker recovery (PR 13).

Pins the degradation contract end to end: the ``KTPU_FAULTS`` harness
is a bit-identical no-op when unarmed and fully deterministic when
armed; the batcher's quarantine isolates exactly the poison rows while
healthy riders resolve on device; the circuit breaker runs the
closed → open → half-open → closed round trip under an injected clock;
a crashed pipeline stage drains without leaking arena buffers; and the
chaos load generator drives the whole serving chain through injected
failures with zero non-200s.  CPU-only, tier-1.
"""

import json
import threading
import time

import pytest
import yaml

from kyverno_tpu import faults
from kyverno_tpu.serving import shed as shed_policy
from kyverno_tpu.serving.batcher import (ALL_FAILED_BREAKER_AFTER,
                                         AdmissionBatcher)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No test leaves the process-wide injector armed."""
    yield
    faults.disable()


def pod(labels, name):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'labels': labels},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


# ---------------------------------------------------------------------------
# the injector: parsing, determinism, and the unarmed no-op


class TestInjector:
    def test_unarmed_is_a_noop(self):
        faults.disable()
        assert faults.active() is None
        for site in faults.SITES:
            faults.check(site)  # must not raise, count, or draw
            faults.check_rows(site, [pod({'chaos': 'x'}, 'p')])

    def test_spec_errors_fail_loudly(self):
        for bad in ('site=nope,nth=1', 'site=encode', 'nth=1',
                    'site=encode,nth=x', 'site=encode,nth=1,zap=1',
                    'site=encode,p=1.5', 'site=encode,nth=1,error=Nope'):
            with pytest.raises(faults.FaultSpecError):
                faults.parse(bad)

    def test_nth_fires_exactly_once(self):
        inj = faults.configure('site=encode,nth=2,error=OSError')
        inj.check(faults.SITE_ENCODE)
        with pytest.raises(OSError) as ei:
            inj.check(faults.SITE_ENCODE)
        assert getattr(ei.value, 'ktpu_injected', False)
        assert not getattr(ei.value, 'ktpu_retry_exhausted', False)
        for _ in range(10):
            inj.check(faults.SITE_ENCODE)  # never again
        assert inj.counts() == {faults.SITE_ENCODE: 1}

    def test_exhaust_marks_retry_exhausted(self):
        inj = faults.configure('site=batcher_dispatch,nth=1,exhaust=1')
        with pytest.raises(RuntimeError) as ei:
            inj.check(faults.SITE_BATCHER_DISPATCH)
        assert getattr(ei.value, 'ktpu_retry_exhausted', False)

    def test_probability_draws_replay(self):
        """The same (seed, spec) fires on the same call indices in
        every run — chaos schedules replay deterministically."""
        def fire_pattern():
            inj = faults.Injector(faults.parse('site=h2d,p=0.3,seed=7'))
            fired = []
            for n in range(64):
                try:
                    inj.check(faults.SITE_H2D)
                except RuntimeError:
                    fired.append(n)
            return fired
        first = fire_pattern()
        assert first and len(first) < 64
        assert fire_pattern() == first

    def test_marker_targets_rows(self):
        inj = faults.configure('site=batcher_dispatch,marker=poison')
        inj.check_rows(faults.SITE_BATCHER_DISPATCH,
                       [pod({}, 'clean')])  # no marked row: no fire
        with pytest.raises(RuntimeError):
            inj.check_rows(faults.SITE_BATCHER_DISPATCH,
                           [pod({}, 'a'), pod({'chaos': 'poison'}, 'b')])
        assert inj.marked([pod({'chaos': 'poison'}, 'b'),
                           pod({}, 'a')]) == 1

    def test_fired_faults_count_on_metric(self):
        from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                                       set_global_registry)
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            inj = faults.configure('site=d2h,nth=1')
            with pytest.raises(RuntimeError):
                inj.check(faults.SITE_D2H)
            assert registry.counter_value(faults.FAULTS_INJECTED,
                                          site=faults.SITE_D2H) == 1
        finally:
            set_global_registry(None)


# ---------------------------------------------------------------------------
# poison-batch quarantine: bisection, verdicts, and exact shed counts


class _OracleScanner:
    """Deterministic rows keyed by resource name; per-call log so the
    tests can count sub-dispatches."""

    def __init__(self):
        self.calls = []

    def scan(self, resources, contexts=None, admission=None,
             pctx_factory=None):
        self.calls.append([r['metadata']['name'] for r in resources])
        return [[('row', r['metadata']['name'])] for r in resources]


class _FailNScanner(_OracleScanner):
    """Raise on the first ``n`` scan calls, then serve (a transient
    device error)."""

    def __init__(self, n=1, mark_exhausted=False):
        super().__init__()
        self.failures_left = n
        self.mark_exhausted = mark_exhausted

    def scan(self, resources, contexts=None, admission=None,
             pctx_factory=None):
        if self.failures_left:
            self.failures_left -= 1
            err = RuntimeError('transient device error')
            if self.mark_exhausted:
                err.ktpu_retry_exhausted = True
            raise err
        return super().scan(resources, contexts, admission, pctx_factory)


class _AlwaysFailScanner(_OracleScanner):
    def __init__(self, mark_exhausted=False):
        super().__init__()
        self.mark_exhausted = mark_exhausted
        self.attempts = 0

    def scan(self, resources, contexts=None, admission=None,
             pctx_factory=None):
        self.attempts += 1
        err = RuntimeError('device gone')
        if self.mark_exhausted:
            err.ktpu_retry_exhausted = True
        raise err


def _submit(batcher, scanner, resource):
    return batcher.submit(
        resource=resource, context=None, pctx=None,
        admission=({'userInfo': {'username': 'a'}}, [], {}, 'CREATE'),
        scanner=scanner, policies=['pol'])


def _callbacks():
    calls = {'ok': 0, 'fail': 0}
    return (calls,
            lambda policies: calls.__setitem__('ok', calls['ok'] + 1),
            lambda policies, e: calls.__setitem__('fail',
                                                  calls['fail'] + 1))


class TestQuarantine:
    def test_poison_rows_isolated_riders_resolve(self):
        """The pinned behavior: a marker-armed fault kills any dispatch
        carrying the poison row; bisection isolates EXACTLY that row
        (shed ``poison_row``), every healthy rider resolves on device
        with the fault-free oracle's rows, and the breaker hears
        success (the backend is healthy)."""
        calls, ok, fail = _callbacks()
        faults.configure('site=batcher_dispatch,marker=poison')
        batcher = AdmissionBatcher(window_ms=60_000, max_batch=4,
                                   queue_cap=16, on_success=ok,
                                   on_failure=fail)
        try:
            scanner = _OracleScanner()
            resources = [pod({}, 'a'), pod({'chaos': 'poison'}, 'bad'),
                         pod({}, 'c'), pod({}, 'd')]
            tickets = [_submit(batcher, scanner, r) for r in resources]
            rows = [t.wait(shed_after_s=10.0) for t in tickets]
            assert rows[0] == [('row', 'a')]
            assert rows[2] == [('row', 'c')]
            assert rows[3] == [('row', 'd')]
            assert rows[1] is None
            assert tickets[1].shed_reason == shed_policy.REASON_POISON_ROW
            counts = batcher.sheds.counts()
            assert counts.get(shed_policy.REASON_POISON_ROW) == 1
            assert shed_policy.REASON_SCAN_ERROR not in counts
            # the breaker verdict lands on the batcher thread right
            # after the riders resolve — give it a beat
            deadline = time.monotonic() + 10.0
            while calls['ok'] < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert calls == {'ok': 1, 'fail': 0}
        finally:
            batcher.stop(drain=False)

    def test_transient_singleton_recovers_without_shed(self):
        """A singleton failure gets one solo re-dispatch: a transient
        device error resolves the rider with NO shed at all."""
        calls, ok, fail = _callbacks()
        batcher = AdmissionBatcher(window_ms=5, queue_cap=16,
                                   on_success=ok, on_failure=fail)
        try:
            scanner = _FailNScanner(n=1)
            ticket = _submit(batcher, scanner, pod({}, 'a'))
            assert ticket.wait(shed_after_s=10.0) == [('row', 'a')]
            assert batcher.sheds.counts() == {}
            deadline = time.monotonic() + 10.0
            while calls['ok'] < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert calls == {'ok': 1, 'fail': 0}
        finally:
            batcher.stop(drain=False)

    def test_all_poison_batch_is_breaker_neutral(self):
        """A dispatch whose only casualties are row-attributed poison
        sheds fires NEITHER breaker callback — an unlucky all-poison
        batch must not quarantine the whole policy set — until
        ALL_FAILED_BREAKER_AFTER consecutive all-failed dispatches
        escalate it."""
        calls, ok, fail = _callbacks()
        batcher = AdmissionBatcher(window_ms=5, queue_cap=16,
                                   on_success=ok, on_failure=fail)
        try:
            scanner = _AlwaysFailScanner()
            for k in range(ALL_FAILED_BREAKER_AFTER):
                ticket = _submit(batcher, scanner, pod({}, f'p{k}'))
                assert ticket.wait(shed_after_s=10.0) is None
                assert ticket.shed_reason == \
                    shed_policy.REASON_POISON_ROW
                # serialize dispatches: each submit must be its own
                # dispatch for the consecutive-strike count to tick,
                # and the verdict lands just after the solo retry
                deadline = time.monotonic() + 10.0
                want = 2 * (k + 1)  # original + solo retry per round
                while scanner.attempts < want and \
                        time.monotonic() < deadline:
                    time.sleep(0.005)
                assert scanner.attempts == want
                time.sleep(0.05)
                if k + 1 < ALL_FAILED_BREAKER_AFTER:
                    assert calls == {'ok': 0, 'fail': 0}, calls
            deadline = time.monotonic() + 10.0
            while calls['fail'] < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert calls == {'ok': 0, 'fail': 1}
        finally:
            batcher.stop(drain=False)

    def test_retry_exhausted_is_wholesale_evidence(self):
        """A retry-exhausted failure (the pipeline burned its whole
        KTPU_STAGE_RETRIES budget) sheds ``stage_retry_exhausted`` and
        counts as a breaker failure on the FIRST dispatch."""
        calls, ok, fail = _callbacks()
        batcher = AdmissionBatcher(window_ms=5, queue_cap=16,
                                   on_success=ok, on_failure=fail)
        try:
            scanner = _AlwaysFailScanner(mark_exhausted=True)
            ticket = _submit(batcher, scanner, pod({}, 'a'))
            assert ticket.wait(shed_after_s=10.0) is None
            assert ticket.shed_reason == \
                shed_policy.REASON_STAGE_RETRY_EXHAUSTED
            deadline = time.monotonic() + 10.0
            while calls['fail'] < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert calls == {'ok': 0, 'fail': 1}
        finally:
            batcher.stop(drain=False)


# ---------------------------------------------------------------------------
# breaker lifecycle: the full round trip under an injected clock


class _Clock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


class TestBreaker:
    def _registry(self, **kw):
        from kyverno_tpu.serving.breaker import BreakerRegistry
        clock = _Clock()
        return clock, BreakerRegistry(clock=clock, base_s=1.0,
                                      max_s=60.0, **kw)

    def test_round_trip_closed_open_half_open_closed(self):
        from kyverno_tpu.serving import breaker as breaker_mod
        from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                                       set_global_registry)
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            opened = []
            clock, breakers = self._registry(failure_limit=2,
                                             on_open=opened.append)
            key = ('k', 1)
            assert breakers.allow(key) == breaker_mod.CLOSED
            assert breakers.record_failure(key, ['pol'], 'e1') == \
                breaker_mod.CLOSED
            assert breakers.record_failure(key, ['pol'], 'e2') == \
                breaker_mod.OPEN
            assert opened == [1]
            assert breakers.allow(key) == breaker_mod.OPEN
            assert registry.gauge_value(breaker_mod.BREAKER_STATE,
                                        state=breaker_mod.OPEN) == 1
            report = breaker_mod.debug_report()
            assert report['enabled']
            row = next(r for r in report['breakers']
                       if r['key'] == repr(key))
            assert row['state'] == breaker_mod.OPEN
            assert row['failures'] == 2 and row['trips'] == 1
            assert row['reopens_in_s'] > 0
            # backoff elapsed: exactly one caller gets the probe
            clock.now += row['reopens_in_s'] + 0.01
            assert breakers.allow(key) == breaker_mod.PROBE
            assert breakers.allow(key) == breaker_mod.OPEN
            assert breakers.state(key) == breaker_mod.HALF_OPEN
            # probe success: entry gone, device path re-admitted
            breakers.record_success(key)
            assert breakers.state(key) == breaker_mod.CLOSED
            assert breakers.allow(key) == breaker_mod.CLOSED
            assert registry.gauge_value(breaker_mod.BREAKER_STATE,
                                        state=breaker_mod.OPEN) == 0
        finally:
            set_global_registry(None)

    def test_probe_failure_reopens_with_doubled_backoff(self):
        from kyverno_tpu.serving import breaker as breaker_mod
        clock, breakers = self._registry(failure_limit=1)
        key = ('k', 2)
        breakers.record_failure(key, ['pol'], 'boom')
        first_backoff = next(
            r for r in breakers.report()
            if r['key'] == repr(key))['reopens_in_s']
        clock.now += first_backoff + 0.01
        assert breakers.allow(key) == breaker_mod.PROBE
        assert breakers.record_failure(key, ['pol'], 'again') == \
            breaker_mod.OPEN
        second_backoff = next(
            r for r in breakers.report()
            if r['key'] == repr(key))['reopens_in_s']
        assert second_backoff > first_backoff * 1.5

    def test_probe_slot_aborts_and_self_heals(self):
        from kyverno_tpu.serving import breaker as breaker_mod
        clock, breakers = self._registry(failure_limit=1)
        key = ('k', 3)
        breakers.record_failure(key, ['pol'], 'boom')
        clock.now += 100.0
        assert breakers.allow(key) == breaker_mod.PROBE
        # slot held: everyone else sheds...
        assert breakers.allow(key) == breaker_mod.OPEN
        # ...until the holder aborts (scanner still building)
        breakers.probe_abort(key)
        assert breakers.allow(key) == breaker_mod.PROBE
        # a probe that never reports back must not wedge the breaker:
        # a full backoff-sized window later the slot re-opens
        clock.now += 100.0
        assert breakers.allow(key) == breaker_mod.PROBE

    def test_cap_evicts_closed_first_and_counts(self):
        from kyverno_tpu.serving import breaker as breaker_mod
        from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                                       set_global_registry)
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            _clock, breakers = self._registry(failure_limit=3, cap=2)
            breakers.record_failure(('closed', 1), ['pol'], 'e')
            breakers.record_failure(('open', 1), ['pol'], 'e')
            breakers.record_failure(('open', 1), ['pol'], 'e')
            breakers.record_failure(('open', 1), ['pol'], 'e')
            assert breakers.state(('open', 1)) == breaker_mod.OPEN
            # at cap: the CLOSED entry is the victim, not the open one
            breakers.record_failure(('new', 1), ['pol'], 'e')
            assert breakers.state(('closed', 1)) == breaker_mod.CLOSED
            assert breakers.state(('open', 1)) == breaker_mod.OPEN
            assert registry.counter_value(
                breaker_mod.BREAKER_EVICTIONS) == 1
        finally:
            set_global_registry(None)


# ---------------------------------------------------------------------------
# pipeline resilience: stage retries and the no-leak drain


class _Arena:
    """Toy buffer owner: values check out of ``live`` on cleanup or
    on reaching the consumer — anything left is a leak."""

    def __init__(self):
        self.live = set()

    def alloc(self, v):
        self.live.add(v)
        return v

    def release(self, v):
        self.live.discard(v)


class TestPipelineResilience:
    def test_transient_stage_error_retries_transparently(self):
        from kyverno_tpu.compiler.pipeline import ChunkPipeline
        attempts = {'n': 0}

        def flaky(v):
            attempts['n'] += 1
            if attempts['n'] == 1:
                raise RuntimeError('hiccup')
            return v * 10

        pipe = ChunkPipeline([('stage', flaky)], depth=2, retries=1)
        assert list(pipe.run([1, 2, 3])) == [10, 20, 30]
        assert attempts['n'] == 4  # one retry, zero surfaced errors

    def test_exhausted_retries_mark_and_release(self):
        from kyverno_tpu.compiler.pipeline import ChunkPipeline
        arena = _Arena()

        def always_fails(v):
            raise RuntimeError('stage dead')

        pipe = ChunkPipeline(
            [('alloc', arena.alloc), ('boom', always_fails)],
            depth=2, retries=2, cleanup=arena.release)
        with pytest.raises(RuntimeError) as ei:
            list(pipe.run([1]))
        assert getattr(ei.value, 'ktpu_retry_exhausted', False)
        assert getattr(ei.value, 'ktpu_stage', '') == 'boom'
        assert arena.live == set()

    def test_stage_crash_drain_releases_all_buffers(self):
        """The pinned behavior: a mid-stream stage crash ends the run
        with every in-flight chunk's buffers reclaimed — an aborted
        scan leaks nothing."""
        from kyverno_tpu.compiler.pipeline import ChunkPipeline
        arena = _Arena()

        def crash_on_two(v):
            if v == 2:
                raise RuntimeError('chunk 2 kills the stage')
            return v

        pipe = ChunkPipeline(
            [('alloc', arena.alloc), ('eval', crash_on_two)],
            depth=2, retries=0, cleanup=arena.release)
        got = []
        with pytest.raises(RuntimeError):
            for v in pipe.run(range(8)):
                got.append(v)
                arena.release(v)  # the consumer owns yielded chunks
        assert got == [0, 1]
        assert arena.live == set(), f'leaked buffers: {arena.live}'


# ---------------------------------------------------------------------------
# loadgen chaos schedule + the serving chain end to end

ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""


class TestChaosLoadgen:
    def test_poison_marking_is_deterministic_and_isolated(self):
        """poison_ratio=0 (the default) draws the exact same traffic as
        an unmarked cluster, and a poisoned cluster only changes the
        marked rows — the fault-free oracle stays valid."""
        from kyverno_tpu.conformance.loadgen import SyntheticCluster
        base = SyntheticCluster(seed=3)
        off = SyntheticCluster(seed=3, poison_ratio=0.0)
        on = SyntheticCluster(seed=3, poison_ratio=0.25)
        marked = 0
        for i in range(32):
            assert base.request(i) == off.request(i)
            req = on.request(i)
            if on.is_poison(i):
                marked += 1
                labels = req['object']['metadata']['labels']
                assert labels.get('chaos') == 'poison'
                assert req['operation'] == 'CREATE'
                assert not on.is_exception_tenant(
                    req['userInfo']['username'])
        assert marked == on.poison_count(32) == 8

    def test_chaos_wave_end_to_end_zero_non_200(self):
        """The pinned behavior: concurrent synthetic-cluster traffic
        with the poison fault schedule armed answers every request 200
        with the fault-free oracle's verdict, and sheds ``poison_row``
        exactly once per injected poison row."""
        from kyverno_tpu.api.policy import Policy
        from kyverno_tpu.conformance.loadgen import SyntheticCluster
        from kyverno_tpu.policycache import cache as pcache
        from kyverno_tpu.policycache.cache import Cache
        from kyverno_tpu.webhooks.handlers import ResourceHandlers
        from kyverno_tpu.webhooks.server import WebhookServer

        cache = Cache()
        cache.warm_up([Policy(d)
                       for d in yaml.safe_load_all(ENFORCE_POLICY)])
        from kyverno_tpu.config.config import Configuration
        handlers = ResourceHandlers(cache, configuration=Configuration(),
                                    serving_mode='batch')
        server = WebhookServer(handlers, configuration=Configuration())
        try:
            cluster = SyntheticCluster(seed=11, poison_ratio=1 / 6)
            enforce = cache.get_policies(pcache.VALIDATE_ENFORCE, 'Pod',
                                         cluster.namespaces[0])
            if not handlers.wait_device_ready(enforce, timeout=600):
                pytest.skip('device scanner never became ready')
            threads, per_thread = 4, 6
            total = threads * per_thread

            def send(i):
                body, status = server.handle_request(
                    '/validate/fail', cluster.review_bytes(i))
                return status, json.loads(body).get('response')

            faults.disable()
            oracle = {}
            for i in range(total):
                status, resp = oracle[i] = send(i)
                assert status == 200
            faults.configure(cluster.fault_spec())
            before = dict(handlers._get_batcher().stats()['shed'])
            got = [None] * total
            barrier = threading.Barrier(threads)

            def work(tid):
                barrier.wait()
                for j in range(per_thread):
                    k = tid + j * threads
                    got[k] = send(k)

            workers = [threading.Thread(target=work, args=(tid,))
                       for tid in range(threads)]
            for t in workers:
                t.start()
            for t in workers:
                t.join(120)
            faults.disable()
            assert all(s == 200 for s, _r in got)
            assert [r for _s, r in got] == \
                [oracle[i][1] for i in range(total)]
            after = dict(handlers._get_batcher().stats()['shed'])
            shed_poison = after.get('poison_row', 0) - \
                before.get('poison_row', 0)
            assert shed_poison == cluster.poison_count(total) == 4
        finally:
            faults.disable()
            handlers.shutdown()
