"""Tier-1 gate: the ktpu-lint analyzer runs clean over the tree.

``python scripts/analyze.py --strict`` must exit 0 — any new
trace-safety / retrace / taxonomy / knob / catalog violation fails CI
here, before a TPU ever sees the code.  The committed baseline must be
minimal (no stale entries) and every entry justified."""

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

BASELINE = os.path.join(REPO_ROOT, '.ktpu-baseline.json')


def _run_analyzer(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, 'scripts',
                                      'analyze.py'), *args],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})


def test_tree_is_clean_in_strict_mode():
    t0 = time.monotonic()
    proc = _run_analyzer('--strict', '--json')
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report['counts']['active'] == 0, report['active']
    assert report['counts']['stale_baseline'] == 0, \
        report['stale_baseline']
    assert not report['errors'], report['errors']
    # CPU-only CI budget: the whole tree must analyze fast
    assert elapsed < 10.0, f'analyzer took {elapsed:.1f}s (budget 10s)'


def test_baseline_is_minimal_and_justified():
    """Every committed baseline entry still matches a real finding
    (in-process re-run, so a stale entry names itself) and carries a
    non-placeholder justification."""
    from kyverno_tpu.analysis import Analyzer
    with open(BASELINE, encoding='utf-8') as f:
        entries = json.load(f)['entries']
    for e in entries:
        reason = str(e.get('reason', '')).strip()
        assert reason and not reason.startswith('TODO'), \
            f'unjustified baseline entry: {e}'
    analyzer = Analyzer(['kyverno_tpu', 'scripts', 'bench.py'],
                        REPO_ROOT, baseline_path=BASELINE)
    report = analyzer.run()
    assert not report.stale_baseline, report.stale_baseline
    assert not report.active, [f.render() for f in report.active]
    # the baseline is exercised, not vestigial: each entry matched
    assert len(report.baselined) >= len(entries)


def test_analyzer_catches_planted_violation(tmp_path):
    """End-to-end through the driver: a rogue file with a host sync in
    a jit function must flip --strict to nonzero."""
    rogue = os.path.join(REPO_ROOT, 'kyverno_tpu', '_rogue_lint.py')
    with open(rogue, 'w') as f:
        f.write('import jax\n\n'
                'def _f(t):\n'
                '    return t.item()\n\n'
                '_jf = jax.jit(_f)\n')
    try:
        proc = _run_analyzer('--strict')
        assert proc.returncode != 0
        assert 'KTPU101' in proc.stdout
    finally:
        os.unlink(rogue)


def test_graph_dump_debug_mode():
    """--graph-dump prints resolved callees + taint facts for a named
    function, and --json emits a machine-readable dump."""
    proc = _run_analyzer('--graph-dump', 'ChunkPipeline._worker')
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'ChunkPipeline._worker' in proc.stdout
    assert 'callees:' in proc.stdout
    assert 'install_capture' in proc.stdout  # resolved cross-module
    proc = _run_analyzer('--graph-dump', 'ChunkPipeline._worker',
                         '--json')
    assert proc.returncode == 0
    dumps = json.loads(proc.stdout)
    assert dumps and dumps[0]['class'] == 'ChunkPipeline'
    assert any(c['qualname'].endswith('install_capture')
               for c in dumps[0]['callees'])
    # unknown names are a distinct exit code, not a crash
    proc = _run_analyzer('--graph-dump', 'no_such_function_xyz')
    assert proc.returncode == 2


def test_knob_table_matches_registry():
    """--knob-table output covers every registered knob, and the README
    carries the generated table (docs cannot drift from the registry)."""
    from kyverno_tpu.analysis.knobs import KNOBS
    proc = _run_analyzer('--knob-table')
    assert proc.returncode == 0
    readme = open(os.path.join(REPO_ROOT, 'README.md'),
                  encoding='utf-8').read()
    for name in KNOBS:
        assert f'`{name}`' in proc.stdout, name
        assert name in readme, f'{name} missing from README knob table'


def test_rule_ids_documented_in_readme():
    from kyverno_tpu.analysis import RULES
    readme = open(os.path.join(REPO_ROOT, 'README.md'),
                  encoding='utf-8').read()
    for rid in RULES:
        assert rid in readme, f'{rid} missing from README rule table'
