"""The reference's arithmetic cross-type case matrix, ported verbatim
from /root/reference/pkg/engine/jmespath/functions_test.go
(Test_Add:540, Test_Subtract:639, Test_Multiply:738, Test_Divide:837,
Test_Modulo:975) per the operator semantics in
pkg/engine/jmespath/arithmetic.go: quantity × duration × scalar for
add / subtract / multiply / divide / modulo, including the ambiguous
'13'-as-quantity parses and every divide/modulo-by-zero form.

Each case is (expression, expected) where expected is a float (scalar
result), a string (canonical quantity/duration form), or ERR.
"""

import pytest

from kyverno_tpu.engine import jmespath as jp

ERR = object()

ADD = [
    # Scalar
    ("add(`12`, `13`)", 25.0),
    ("add('12', '13s')", ERR),
    ("add(`12`, '13Ki')", ERR),
    ("add(`12`, '13')", ERR),
    # Quantity
    ("add('12Ki', '13Ki')", "25Ki"),
    ("add('12Ki', '13')", "12301"),
    ("add('12Ki', '13s')", ERR),
    ("add('12Ki', `13`)", ERR),
    # Duration
    ("add('12s', '13s')", "25s"),
    ("add('12s', '13')", ERR),
    ("add('12s', '13Ki')", ERR),
]

SUBTRACT = [
    # Scalar
    ("subtract(`12`, `13`)", -1.0),
    ("subtract('12', '13s')", ERR),
    ("subtract(`12`, '13Ki')", ERR),
    ("subtract(`12`, '13')", ERR),
    # Quantity
    ("subtract('12Ki', '13Ki')", "-1Ki"),
    ("subtract('12Ki', '13')", "12275"),
    ("subtract('12Ki', '13s')", ERR),
    ("subtract('12Ki', `13`)", ERR),
    # Duration
    ("subtract('12s', '13s')", "-1s"),
    ("subtract('12s', '13')", ERR),
    ("subtract('12s', '13Ki')", ERR),
]

MULTIPLY = [
    # Quantity
    ("multiply('12Ki', `2`)", "24Ki"),
    ("multiply('12Ki', '12Ki')", ERR),
    ("multiply('12Ki', '12')", ERR),
    ("multiply('12Ki', '12s')", ERR),
    # Duration
    ("multiply('12s', `2`)", "24s"),
    ("multiply('12s', '12Ki')", ERR),
    ("multiply('12s', '12')", ERR),
    ("multiply('12s', '12s')", ERR),
    # Scalar
    ("multiply(`2.5`, `2.5`)", 6.25),
    ("multiply(`2.5`, '12Ki')", "30Ki"),
    ("multiply(`2.5`, '12')", "30"),
    ("multiply(`2.5`, '40s')", "1m40s"),
]

DIVIDE = [
    # Quantity
    ("divide('12Ki', `3`)", "4Ki"),
    ("divide('12Ki', '2Ki')", 6.0),
    ("divide('12Ki', '200')", 61.0),
    ("divide('12Ki', '2s')", ERR),
    # Duration
    ("divide('12s', `3`)", "4s"),
    ("divide('12s', '5s')", 2.4),
    ("divide('12s', '4Ki')", ERR),
    ("divide('12s', '4')", ERR),
    # Scalar
    ("divide(`14`, `3`)", 4.666666666666667),
    ("divide(`14`, '5s')", ERR),
    ("divide(`14`, '5Ki')", ERR),
    ("divide(`14`, '5')", ERR),
    # Divide by 0
    ("divide(`14`, `0`)", ERR),
    ("divide('4Ki', `0`)", ERR),
    ("divide('4Ki', '0Ki')", ERR),
    ("divide('4', `0`)", ERR),
    ("divide('4', '0')", ERR),
    ("divide('4s', `0`)", ERR),
    ("divide('4s', '0s')", ERR),
]

MODULO = [
    # Quantity
    ("modulo('12', '13s')", ERR),
    ("modulo('12Ki', '13s')", ERR),
    ("modulo('12Ki', `13`)", ERR),
    ("modulo('12Ki', '5Ki')", "2Ki"),
    # Duration
    ("modulo('13s', '12')", ERR),
    ("modulo('13s', '12Ki')", ERR),
    ("modulo('13s', '2s')", "1s"),
    ("modulo('13s', `2`)", ERR),
    # Scalar
    ("modulo(`13`, '12')", ERR),
    ("modulo(`13`, '12Ki')", ERR),
    ("modulo(`13`, '5s')", ERR),
    ("modulo(`13`, `5`)", 3.0),
    # Modulo by 0
    ("modulo(`14`, `0`)", ERR),
    ("modulo('4Ki', `0`)", ERR),
    ("modulo('4Ki', '0Ki')", ERR),
    ("modulo('4', `0`)", ERR),
    ("modulo('4', '0')", ERR),
    ("modulo('4s', `0`)", ERR),
    ("modulo('4s', '0s')", ERR),
]


def run_matrix(cases):
    for expr, expected in cases:
        if expected is ERR:
            with pytest.raises(Exception):
                jp.search(expr, "")
            continue
        result = jp.search(expr, "")
        if isinstance(expected, float):
            assert isinstance(result, float), \
                f'{expr}: expected float, got {type(result).__name__} {result!r}'
            assert result == expected, f'{expr}: {result!r} != {expected!r}'
        else:
            assert isinstance(result, str), \
                f'{expr}: expected str, got {type(result).__name__} {result!r}'
            assert result == expected, f'{expr}: {result!r} != {expected!r}'


class TestArithmeticMatrix:
    def test_add(self):
        run_matrix(ADD)

    def test_subtract(self):
        run_matrix(SUBTRACT)

    def test_multiply(self):
        run_matrix(MULTIPLY)

    def test_divide(self):
        run_matrix(DIVIDE)

    def test_modulo(self):
        run_matrix(MODULO)


class TestDivideScaleQuirks:
    """inf.Dec QuoRound truncation uses the quantities' AsDec scales —
    NEGATIVE for decimal-SI suffixes ('3G' is inf.NewDec(3, -9)), so
    division quantizes to the coarser operand's unit
    (arithmetic.go:197 Quantity.Divide)."""

    def test_milli_scale_truncation(self):
        assert jp.search("divide('100m', '3')", "") == 0.033
        assert jp.search("divide('2500m', '3')", "") == 0.833

    def test_decimal_suffix_negative_scale(self):
        # scale -9: the quotient truncates to multiples of 1e9, so BOTH
        # quotients collapse to 0 (a faithful reference quirk —
        # inf.Dec.QuoRound at the AsDec scale of the coarser operand)
        assert jp.search("divide('3G', '2G')", "") == 0.0
        assert jp.search("divide('4G', '2G')", "") == 0.0
        # a suffix-less divisor (AsDec scale 0) restores resolution
        assert jp.search("divide('4G', '2000000000')", "") == 2.0

    def test_mixed_scales(self):
        # '3G' scale -9, '200' scale 0 -> max 0 -> plain truncation
        assert jp.search("divide('3G', '200')", "") == 15000000.0
