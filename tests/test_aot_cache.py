"""Persistent AOT executable cache + background warm-up (ISSUE 2).

Store semantics (hit/miss, corruption tolerance, LRU eviction, atomic
writes), cache-key scoping (policy set / version mismatch / multi-
device refusal), warmer lifecycle (including the KTPU_WARM=0 no-op),
and the acceptance criterion: a second process starting against a
populated cache performs ZERO fresh XLA compiles for the cached policy
set (asserted via the kyverno_tpu_compile_cache aot_load/miss
counters), with bit-identical scan output vs the uncached path.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from kyverno_tpu.aotcache import keys as aot_keys
from kyverno_tpu.aotcache.store import AotStore, reset_default_store
from kyverno_tpu.aotcache.warmer import Warmer
from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                               set_global_registry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate_default_store():
    reset_default_store()
    yield
    reset_default_store()
    set_global_registry(None)


# ---------------------------------------------------------------------------
# store


class TestStore:
    def test_miss_then_hit(self, tmp_path):
        store = AotStore(root=str(tmp_path))
        assert store.load('k' * 32) is None          # miss
        assert store.put('k' * 32, b'payload-bytes')
        assert store.load('k' * 32) == b'payload-bytes'  # hit
        st = store.stats()
        assert st['entries'] == 1 and st['bytes'] > len(b'payload-bytes')

    def test_corrupt_entry_dropped_not_crashed(self, tmp_path):
        store = AotStore(root=str(tmp_path))
        store.put('deadbeef', b'x' * 256)
        path = store.path('deadbeef')
        raw = bytearray(open(path, 'rb').read())
        raw[-1] ^= 0xFF  # flip a payload bit under the digest
        open(path, 'wb').write(bytes(raw))
        assert store.load('deadbeef') is None
        assert not os.path.exists(path), 'corrupt entry must be deleted'
        # truncated-below-header entries are equally a miss
        open(store.path('cafe'), 'wb').write(b'KT')
        assert store.load('cafe') is None
        assert not os.path.exists(store.path('cafe'))

    def test_lru_eviction_respects_byte_budget(self, tmp_path):
        blob = b'z' * 1000
        frame = 38  # magic + sha256
        store = AotStore(root=str(tmp_path),
                         max_bytes=3 * (len(blob) + frame))
        now = time.time()
        for i, key in enumerate(('old', 'mid', 'new')):
            store.put(key, blob)
            os.utime(store.path(key), (now - 100 + i, now - 100 + i))
        store.put('newest', blob)  # over budget: LRU ('old') evicted
        assert store.load('old') is None
        assert store.load('mid') is not None
        assert store.load('newest') is not None
        assert store.stats()['entries'] == 3

    def test_load_refreshes_lru_position(self, tmp_path):
        blob = b'z' * 1000
        store = AotStore(root=str(tmp_path), max_bytes=3 * 1100)
        now = time.time()
        for i, key in enumerate(('a', 'b', 'c')):
            store.put(key, blob)
            os.utime(store.path(key), (now - 100 + i, now - 100 + i))
        store.load('a')  # touch: 'a' becomes most-recent, 'b' is LRU
        store.put('d', blob)
        assert store.load('b') is None
        assert store.load('a') is not None

    def test_atomic_writes_leave_no_tmp(self, tmp_path):
        store = AotStore(root=str(tmp_path))
        for i in range(5):
            store.put(f'key{i}', os.urandom(2048))
        assert not [n for n in os.listdir(tmp_path) if n.endswith('.tmp')]

    def test_env_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv('KTPU_AOT_CACHE_DIR', str(tmp_path / 'via-env'))
        store = AotStore()
        assert store.root == str(tmp_path / 'via-env')
        monkeypatch.setenv('KTPU_AOT', '0')
        assert not AotStore().enabled

    def test_publishes_size_gauges(self, tmp_path):
        reg = MetricsRegistry()
        set_global_registry(reg)
        store = AotStore(root=str(tmp_path))
        store.put('k1', b'x' * 100)
        assert reg.gauge_value('kyverno_tpu_aot_cache_entries') == 1.0
        assert reg.gauge_value('kyverno_tpu_aot_cache_size_bytes') > 100

    def test_undecodable_blob_is_evicted_by_loader(self, tmp_path):
        from kyverno_tpu.compiler import aot
        store = AotStore(root=str(tmp_path))
        store.put('badcodec', b'Qnot-a-real-codec-blob')
        assert aot.load_executable('badcodec', store=store) is None
        assert store.load('badcodec') is None, 'bad entry must be dropped'


# ---------------------------------------------------------------------------
# keys


def _single_device(monkeypatch):
    monkeypatch.setattr(aot_keys.jax, 'local_devices',
                        lambda backend=None: [object()])


class TestKeys:
    PACKED = {'pk_int8': np.zeros((4, 8), np.int8),
              'pk_float64': np.zeros((4, 2), np.float64)}

    def test_key_scopes_policy_set_and_version(self, monkeypatch):
        _single_device(monkeypatch)
        k1 = aot_keys.executable_cache_key('fp-one', self.PACKED)
        k2 = aot_keys.executable_cache_key('fp-two', self.PACKED)
        assert k1 and k2 and k1 != k2
        # version-key mismatch: a format bump invalidates every entry
        monkeypatch.setattr(aot_keys, 'AOT_VERSION',
                            aot_keys.AOT_VERSION + 1)
        k1_v2 = aot_keys.executable_cache_key('fp-one', self.PACKED)
        assert k1_v2 and k1_v2 != k1

    def test_version_mismatch_misses_in_store(self, tmp_path, monkeypatch):
        _single_device(monkeypatch)
        store = AotStore(root=str(tmp_path))
        k_old = aot_keys.executable_cache_key('fp', self.PACKED)
        store.put(k_old, b'serialized-under-old-version')
        monkeypatch.setattr(aot_keys, 'AOT_VERSION',
                            aot_keys.AOT_VERSION + 1)
        k_new = aot_keys.executable_cache_key('fp', self.PACKED)
        assert store.load(k_new) is None    # stale entry never loads
        assert store.load(k_old) is not None  # ...but is not destroyed

    def test_key_scopes_batch_layout(self, monkeypatch):
        _single_device(monkeypatch)
        other = {'pk_int8': np.zeros((8, 8), np.int8),
                 'pk_float64': np.zeros((8, 2), np.float64)}
        assert aot_keys.executable_cache_key('fp', self.PACKED) != \
            aot_keys.executable_cache_key('fp', other)

    def test_multi_device_host_refuses_key(self):
        # the tier-1 env forces 8 virtual CPU devices; deserialize_and_
        # load would mis-load a 1-device executable as 8-shard SPMD
        import jax
        if len(jax.local_devices(backend='cpu')) == 1:
            pytest.skip('env has a single CPU device')
        assert aot_keys.executable_cache_key('fp', self.PACKED) is None

    def test_fingerprint_stable(self):
        fp = aot_keys.policy_set_fingerprint
        a = [{'spec': {'rules': [1]}, 'metadata': {'name': 'x'}}]
        b = [{'metadata': {'name': 'x'}, 'spec': {'rules': [1]}}]
        assert fp(a) == fp(b)          # key order never matters
        assert fp(a) != fp([{'metadata': {'name': 'y'}}])


# ---------------------------------------------------------------------------
# warmer


class TestWarmer:
    def test_noop_when_disabled(self, monkeypatch):
        monkeypatch.setenv('KTPU_WARM', '0')
        calls = []
        w = Warmer(lambda: calls.append(1))
        assert w.start() is False
        assert w.state == 'disabled'
        assert w.wait(0.1) is True       # never blocks callers
        assert not calls, 'warm_fn must not run when disabled'
        assert not [t for t in threading.enumerate()
                    if t.name.startswith('ktpu-aot-warmer')]

    def test_ready_records_duration_histogram(self):
        reg = MetricsRegistry()
        w = Warmer(lambda: 'warmed 3 executables', registry=reg,
                   enabled=True)
        assert w.start() is True
        assert w.wait(10.0)
        assert w.state == 'ready' and w.ready
        assert w.detail == 'warmed 3 executables'
        assert reg.histogram_count('kyverno_tpu_aot_warm_duration_seconds',
                                   target='admission', state='ready') == 1

    def test_failure_is_contained(self):
        reg = MetricsRegistry()

        def boom():
            raise RuntimeError('no backend')
        w = Warmer(boom, name='scan', registry=reg, enabled=True)
        w.run_sync()
        assert w.state == 'failed' and not w.ready
        assert 'no backend' in w.error
        assert reg.histogram_count('kyverno_tpu_aot_warm_duration_seconds',
                                   target='scan', state='failed') == 1

    def test_start_is_idempotent(self):
        calls = []
        w = Warmer(lambda: calls.append(1) or 'ok', enabled=True)
        assert w.start() and w.start()
        w.wait(10.0)
        assert calls == [1]

    def test_setup_starts_warmer(self):
        from kyverno_tpu.cmd.internal import Setup
        setup = Setup('t', args=['--disable-metrics'])
        w = setup.start_aot_warmer(lambda: 'scanner serving')
        assert setup.aot_warmer is w
        assert w.wait(10.0) and w.state == 'ready'
        assert w.detail == 'scanner serving'

    def test_webhook_warmup_status(self):
        from types import SimpleNamespace
        from kyverno_tpu.webhooks.server import WebhookServer
        status = WebhookServer.warmup_status
        body, code = status(SimpleNamespace(warmer=None))
        assert (body['state'], code) == ('disabled', 200)
        w = Warmer(lambda: 'ok', enabled=True)
        body, code = status(SimpleNamespace(warmer=w))
        assert (body['state'], code) == ('pending', 503)
        w.run_sync()
        body, code = status(SimpleNamespace(warmer=w))
        assert (body['state'], code) == ('ready', 200)
        assert 'duration_s' in body


# ---------------------------------------------------------------------------
# acceptance: second process = zero fresh compiles, bit-identical output

_SECOND_PROC_SCRIPT = r'''
import json, sys
from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import device as devtel
from kyverno_tpu.observability.metrics import MetricsRegistry

POLICY = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'require-labels', 'annotations': {
        'pod-policies.kyverno.io/autogen-controllers': 'none'}},
    'spec': {'validationFailureAction': 'Enforce', 'rules': [
        {'name': 'check-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'app label required',
                      'pattern': {'metadata': {'labels': {'app': '?*'}}}}},
    ]}}


def pod(i):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{i}', 'namespace': 'default',
                         'labels': {'app': 'x'} if i % 2 else {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}


reg = devtel.configure(MetricsRegistry())
from kyverno_tpu.compiler.scan import BatchScanner
scanner = BatchScanner([Policy(POLICY)])
status, detail, match = scanner.scan_statuses([pod(i) for i in range(4)])
from kyverno_tpu.compiler import aot
aot.flush_stores()
C = 'kyverno_tpu_compile_cache_requests_total'
print(json.dumps({
    'miss': reg.counter_value(C, result='miss'),
    'aot_load': reg.counter_value(C, result='aot_load'),
    'aot_store': reg.counter_value(C, result='aot_store'),
    'status': status.tolist(),
    'detail': detail.tolist(),
    'match': match.tolist(),
}))
'''


def _run_fresh_process(cache_dir, aot_enabled=True, timeout=240):
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'PYTHONPATH': REPO,
        'KTPU_AOT': '1' if aot_enabled else '0',
        'KTPU_AOT_CACHE_DIR': os.path.join(str(cache_dir), 'aot'),
        'KTPU_COMPILE_CACHE': os.path.join(str(cache_dir), 'xla'),
    })
    out = subprocess.run([sys.executable, '-c', _SECOND_PROC_SCRIPT],
                         env=env, cwd=REPO, capture_output=True,
                         text=True, timeout=timeout)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_second_process_zero_fresh_compiles(tmp_path):
    """ISSUE 2 acceptance: process 1 compiles + persists; process 2
    (fresh interpreter, cold jit caches, same policy set) serves
    entirely from aot_load with zero misses; a third process with the
    cache disabled recompiles and produces bit-identical matrices."""
    first = _run_fresh_process(tmp_path)
    assert first['miss'] >= 1, first
    assert first['aot_store'] >= 1, first
    second = _run_fresh_process(tmp_path)
    assert second['miss'] == 0, second
    assert second['aot_load'] >= 1, second
    uncached = _run_fresh_process(tmp_path, aot_enabled=False)
    assert uncached['miss'] >= 1, uncached
    for field in ('status', 'detail', 'match'):
        assert second[field] == first[field] == uncached[field], field
