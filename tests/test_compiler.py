import itertools
import random

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

POLICY_PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: disallow-latest-tag
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: require-image-tag
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "An image tag is required."
        pattern:
          spec:
            containers:
              - image: "!*:latest"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-resources
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: validate-resources
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "resource requests and limits required"
        pattern:
          spec:
            containers:
              - resources:
                  requests:
                    memory: "?*"
                    cpu: "?*"
                  limits:
                    memory: "<4Gi"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: check-replicas
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: replica-bounds
      match: {any: [{resources: {kinds: [Deployment]}}]}
      validate:
        message: "replicas must be 1-10"
        pattern:
          spec:
            replicas: "1-10"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: conditional-pull-policy
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: latest-needs-always
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "latest images need Always pull policy"
        pattern:
          spec:
            containers:
              - (image): "*:latest"
                imagePullPolicy: Always
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: no-host-network
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: host-network-false
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "host network not allowed"
        pattern:
          spec:
            =(hostNetwork): false
"""


def load_pack():
    return [Policy(d) for d in yaml.safe_load_all(POLICY_PACK)]


def make_pod(rng):
    """Randomized pod exercising edge cases."""
    containers = []
    for i in range(rng.randint(1, 4)):
        c = {'name': f'c{i}'}
        img = rng.choice(['nginx:1.25', 'nginx:latest', 'redis', 'app:v2',
                          'ghcr.io/x/y:latest', ''])
        c['image'] = img
        if rng.random() < 0.7:
            c['imagePullPolicy'] = rng.choice(['Always', 'IfNotPresent'])
        if rng.random() < 0.8:
            res = {}
            if rng.random() < 0.8:
                res['requests'] = {
                    'memory': rng.choice(['64Mi', '1Gi', '', '128974848']),
                    'cpu': rng.choice(['100m', '1', '0.5']),
                }
            if rng.random() < 0.8:
                res['limits'] = {'memory': rng.choice(
                    ['128Mi', '4Gi', '8Gi', '3.9Gi', '4096Mi'])}
            c['resources'] = res
        containers.append(c)
    spec = {'containers': containers}
    r = rng.random()
    if r < 0.2:
        spec['hostNetwork'] = True
    elif r < 0.4:
        spec['hostNetwork'] = False
    pod = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': f'pod-{rng.randint(0, 999)}',
                        'namespace': 'default'},
           'spec': spec}
    if rng.random() < 0.1:
        del pod['spec']['containers']
    return pod


def make_deployment(rng):
    replicas = rng.choice([0, 1, 5, 10, 11, '3', None])
    spec = {}
    if replicas is not None:
        spec['replicas'] = replicas
    return {'apiVersion': 'apps/v1', 'kind': 'Deployment',
            'metadata': {'name': 'd', 'namespace': 'default'},
            'spec': spec}


class TestCompile:
    def test_pack_fully_compiles(self):
        cps = compile_policies(load_pack())
        assert len(cps.programs) == 5
        assert cps.host_rules == []

    def test_fallback_for_unsupported(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: x
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: needs-vars
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        pattern:
          metadata:
            name: "{{request.object.metadata.namespace}}-*"
"""))
        cps = compile_policies([policy])
        assert len(cps.programs) == 0
        assert len(cps.host_rules) == 1


class TestEquivalence:
    def test_device_vs_host(self):
        policies = load_pack()
        engine = Engine()
        rng = random.Random(7)
        resources = [make_pod(rng) for _ in range(60)] + \
                    [make_deployment(rng) for _ in range(20)]

        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)

        for resource, responses in zip(resources, scanned):
            host = {}
            for policy in policies:
                resp = engine.apply_background_checks(
                    PolicyContext(policy, new_resource=resource))
                if resp.policy_response.rules:
                    host[policy.name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            got = {}
            for resp in responses:
                if resp.policy_response.rules:
                    got[resp.policy_response.policy_name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            assert got == host, f'divergence on {resource}'


class TestScannerShapes:
    def test_empty_batch(self):
        assert BatchScanner(load_pack()).scan([]) == []

    def test_non_matching_kind(self):
        scanner = BatchScanner(load_pack())
        out = scanner.scan([{'apiVersion': 'v1', 'kind': 'Service',
                             'metadata': {'name': 's', 'namespace': 'x'},
                             'spec': {}}])
        assert out == [[]]


ANCHOR_POLICIES = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-proxy
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: must-have-proxy
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "istio-proxy container required"
        pattern:
          spec:
            ^(containers):
              - name: istio-proxy
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: no-host-network-key
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: no-hostnetwork
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "hostNetwork may not be set"
        pattern:
          spec:
            X(hostNetwork): "null"
"""


class TestAnchorEquivalence:
    def test_exists_and_negation_anchors(self):
        policies = [Policy(d) for d in yaml.safe_load_all(ANCHOR_POLICIES)]
        cps = compile_policies(policies)
        assert cps.host_rules == []
        engine = Engine()
        cases = [
            {'spec': {'containers': []}},                       # exists: fail
            {'spec': {'containers': [{'name': 'istio-proxy'}]}},  # pass
            {'spec': {'containers': [{'name': 'app'}]}},        # exists: fail
            {'spec': {}},                                       # missing: pass
            {'spec': {'hostNetwork': True,
                      'containers': [{'name': 'istio-proxy'}]}},  # neg: fail
        ]
        resources = [{'apiVersion': 'v1', 'kind': 'Pod',
                      'metadata': {'name': f'p{i}', 'namespace': 'd'}, **c}
                     for i, c in enumerate(cases)]
        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)
        for resource, responses in zip(resources, scanned):
            host = {}
            for policy in policies:
                resp = engine.apply_background_checks(
                    PolicyContext(policy, new_resource=resource))
                if resp.policy_response.rules:
                    host[policy.name] = {r.name: (r.status, r.message)
                                         for r in resp.policy_response.rules}
            got = {r.policy_response.policy_name:
                   {x.name: (x.status, x.message)
                    for x in r.policy_response.rules}
                   for r in responses if r.policy_response.rules}
            assert got == host, f'divergence on {resource}'
