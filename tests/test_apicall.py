"""APICall / ServiceCall / imageRegistry context transports
(reference: pkg/engine/apicall/apiCall.go, pkg/engine/jsonContext.go)."""

import json

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.engine.apicall import APICallExecutor, make_context_loader
from kyverno_tpu.engine.api import PolicyContext, RuleStatus
from kyverno_tpu.engine.context import Context, ContextError
from kyverno_tpu.engine.engine import Engine


def fake_http(responses):
    calls = []

    def transport(method, url, headers, body, ca_bundle=''):
        calls.append({'method': method, 'url': url, 'headers': headers,
                      'body': body})
        return json.dumps(responses[url]).encode()
    transport.calls = calls
    return transport


class TestAPICall:
    def test_service_get_with_jmespath(self):
        transport = fake_http({'http://svc/data': {'items': [1, 2, 3]}})
        ex = APICallExecutor(http_transport=transport,
                             token_reader=lambda: 'tok')
        ctx = Context()
        result = ex({'name': 'e', 'apiCall': {
            'service': {'url': 'http://svc/data', 'method': 'GET'},
            'jmesPath': 'items | length(@)'}}, ctx)
        assert result == 3
        assert transport.calls[0]['headers']['Authorization'] == 'Bearer tok'

    def test_service_post_data(self):
        transport = fake_http({'http://svc/q': {'ok': True}})
        ex = APICallExecutor(http_transport=transport,
                             token_reader=lambda: '')
        result = ex({'name': 'e', 'apiCall': {
            'service': {'url': 'http://svc/q', 'method': 'POST'},
            'data': [{'key': 'a', 'value': 1}]}}, Context())
        assert result == {'ok': True}
        assert json.loads(transport.calls[0]['body']) == {'a': 1}

    def test_url_path_uses_cluster_client(self):
        def raw(path):
            assert path == '/api/v1/namespaces'
            return json.dumps({'items': [{'metadata': {'name': 'a'}}]}).encode()
        ex = APICallExecutor(raw_abs_path=raw,
                             http_transport=fake_http({}))
        result = ex({'name': 'e', 'apiCall': {
            'urlPath': '/api/v1/namespaces',
            'jmesPath': 'items[0].metadata.name'}}, Context())
        assert result == 'a'

    def test_variable_substitution_in_url(self):
        transport = fake_http({'http://svc/ns/default': {'v': 1}})
        ex = APICallExecutor(http_transport=transport)
        ctx = Context()
        ctx.add_resource({'metadata': {'namespace': 'default'}})
        result = ex({'name': 'e', 'apiCall': {'service': {
            'url': 'http://svc/ns/{{request.object.metadata.namespace}}',
            'method': 'GET'}}}, ctx)
        assert result == {'v': 1}

    def test_errors_are_context_errors(self):
        def boom(*a, **k):
            raise OSError('connection refused')
        ex = APICallExecutor(http_transport=boom)
        with pytest.raises(ContextError):
            ex({'name': 'e', 'apiCall': {
                'service': {'url': 'http://x', 'method': 'GET'}}}, Context())


class TestEngineWiring:
    def test_policy_with_apicall_context(self):
        transport = fake_http({'http://audit/allowed': ['nginx', 'redis']})
        loader = make_context_loader(http_transport=transport,
                                     token_reader=lambda: '')
        engine = Engine(context_loader=loader)
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: allowed-images, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: check
      match: {any: [{resources: {kinds: [Pod]}}]}
      context:
        - name: allowed
          apiCall:
            service: {url: "http://audit/allowed", method: GET}
      validate:
        message: image not allowed
        deny:
          conditions:
            all:
              - key: "{{request.object.spec.containers[0].image}}"
                operator: AnyNotIn
                value: "{{allowed}}"
"""))
        def run(image):
            pod = {'apiVersion': 'v1', 'kind': 'Pod',
                   'metadata': {'name': 'p', 'namespace': 'd'},
                   'spec': {'containers': [{'name': 'c', 'image': image}]}}
            resp = engine.validate(PolicyContext(policy, new_resource=pod))
            return resp.policy_response.rules[0].status
        assert run('nginx') == RuleStatus.PASS
        assert run('evil') == RuleStatus.FAIL

    def test_image_registry_context(self):
        from kyverno_tpu.registry.client import MockRegistryClient
        rclient = MockRegistryClient()
        rclient.add_image('ghcr.io/org/app:v1', 'sha256:' + 'a' * 64)
        loader = make_context_loader(registry_client=rclient)
        engine = Engine(context_loader=loader)
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: img-meta, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: check
      match: {any: [{resources: {kinds: [Pod]}}]}
      context:
        - name: img
          imageRegistry:
            reference: "{{request.object.spec.containers[0].image}}"
      validate:
        message: must resolve
        deny:
          conditions:
            all:
              - key: "{{img.registry}}"
                operator: NotEquals
                value: ghcr.io
"""))
        pod = {'apiVersion': 'v1', 'kind': 'Pod',
               'metadata': {'name': 'p', 'namespace': 'd'},
               'spec': {'containers': [
                   {'name': 'c', 'image': 'ghcr.io/org/app:v1'}]}}
        resp = engine.validate(PolicyContext(policy, new_resource=pod))
        assert resp.policy_response.rules[0].status == RuleStatus.PASS
