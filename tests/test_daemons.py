"""End-to-end daemon wiring: the five deployables sharing one fake
cluster — admission webhook → UpdateRequest → background controller →
generated resource; reports controller → PolicyReport; cert renewal,
webhook config reconciliation, cleanup, init
(reference: cmd/*)."""

import json

import yaml

from kyverno_tpu.cmd.admission_controller import AdmissionController
from kyverno_tpu.cmd.background_controller import BackgroundController
from kyverno_tpu.cmd.cleanup_controller import CleanupDaemon
from kyverno_tpu.cmd.init import cleanup_stale_state
from kyverno_tpu.cmd.internal import Setup, base_parser
from kyverno_tpu.cmd.reports_controller import ReportsController
from kyverno_tpu.dclient.client import FakeClient

GENERATE_POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: add-np}
spec:
  rules:
    - name: default-deny
      match: {any: [{resources: {kinds: [Namespace]}}]}
      generate:
        apiVersion: networking.k8s.io/v1
        kind: NetworkPolicy
        name: default-deny
        namespace: "{{request.object.metadata.name}}"
        data:
          spec: {podSelector: {}, policyTypes: [Ingress]}
""")

AUDIT_POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: need-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: audit
  rules:
    - name: team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: team required
        pattern: {metadata: {labels: {team: "?*"}}}
""")

CLEANUP_POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v2alpha1
kind: ClusterCleanupPolicy
metadata: {name: sweep-temp}
spec:
  schedule: "* * * * *"
  match: {any: [{resources: {kinds: [ConfigMap]}}]}
  conditions:
    all:
      - key: "{{request.object.metadata.labels.temp}}"
        operator: Equals
        value: "true"
""")


def make_setup(client=None):
    return Setup('test', [], base_parser('test'), client=client)


def review(resource):
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {
            'uid': 'u1', 'operation': 'CREATE',
            'kind': {'group': '', 'version': 'v1',
                     'kind': resource.get('kind', '')},
            'namespace': (resource.get('metadata') or {}).get(
                'namespace', ''),
            'object': resource, 'userInfo': {'username': 'test'},
        }}).encode()


class TestAdmissionToGenerate:
    def test_full_generate_flow(self):
        client = FakeClient()
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               GENERATE_POLICY)
        setup = make_setup(client)
        admission = AdmissionController(setup, tls=False)
        admission.tick()  # sync cache + reconcile webhook configs

        # webhook configurations materialized with CA bundle
        vwc = client.get_resource(
            'admissionregistration.k8s.io/v1',
            'ValidatingWebhookConfiguration', '',
            'kyverno-resource-validating-webhook-cfg')
        assert vwc['webhooks']
        assert vwc['webhooks'][0]['clientConfig']['caBundle']

        # admission of a Namespace spawns an UpdateRequest
        ns = {'apiVersion': 'v1', 'kind': 'Namespace',
              'metadata': {'name': 'team-a'}}
        body = admission.server.handle('/validate', review(ns))
        assert json.loads(body)['response']['allowed'] is True
        client.create_resource('v1', 'Namespace', '', ns)
        urs = client.list_resource('kyverno.io/v1beta1', 'UpdateRequest',
                                   'kyverno', None)
        assert len(urs) == 1

        # the background controller drains the UR into the generated
        # resource
        bg = BackgroundController(setup)
        bg.tick()
        nps = client.list_resource('networking.k8s.io/v1', 'NetworkPolicy',
                                   'team-a', None)
        assert len(nps) == 1
        assert nps[0]['metadata']['name'] == 'default-deny'


class TestReportsDaemon:
    def test_scan_to_policy_report(self):
        client = FakeClient()
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               AUDIT_POLICY)
        client.create_resource('v1', 'Pod', 'default', {
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p1', 'namespace': 'default',
                         'uid': 'u-p1', 'labels': {}},
            'spec': {'containers': [{'name': 'c', 'image': 'x'}]}})
        setup = make_setup(client)
        reports = ReportsController(setup)
        reports.tick()
        prs = client.list_resource('wgpolicyk8s.io/v1alpha2',
                                   'PolicyReport', 'default', None)
        assert prs and prs[0]['summary']['fail'] == 1


class TestCleanupDaemon:
    def test_cleanup_deletes_matching(self):
        client = FakeClient()
        client.create_resource('kyverno.io/v2alpha1',
                               'ClusterCleanupPolicy', '', CLEANUP_POLICY)
        client.create_resource('v1', 'ConfigMap', 'default', {
            'apiVersion': 'v1', 'kind': 'ConfigMap',
            'metadata': {'name': 'tmp', 'namespace': 'default',
                         'labels': {'temp': 'true'}}})
        client.create_resource('v1', 'ConfigMap', 'default', {
            'apiVersion': 'v1', 'kind': 'ConfigMap',
            'metadata': {'name': 'keep', 'namespace': 'default'}})
        daemon = CleanupDaemon(make_setup(client))
        daemon.tick()  # "* * * * *" matches every minute
        names = [c['metadata']['name'] for c in client.list_resource(
            'v1', 'ConfigMap', 'default', None)]
        assert names == ['keep']


class TestInitJob:
    def test_removes_stale_state(self):
        client = FakeClient()
        setup = make_setup(client)
        admission = AdmissionController(setup, tls=False)
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               AUDIT_POLICY)
        admission.tick()
        admission.reconciler.heartbeat()
        assert cleanup_stale_state(client) >= 2
        leases = client.list_resource('coordination.k8s.io/v1', 'Lease',
                                      'kyverno', None)
        assert leases == []


class TestCertRenewal:
    def test_ca_and_pair_secrets(self):
        import datetime
        from kyverno_tpu.tls.certs import (CA_SECRET, TLS_SECRET,
                                           CertRenewer, cert_expiry)
        client = FakeClient()
        renewer = CertRenewer(client)
        ca1, cert1, _ = renewer.renew()
        assert client.get_resource('v1', 'Secret', 'kyverno', CA_SECRET)
        assert client.get_resource('v1', 'Secret', 'kyverno', TLS_SECRET)
        # stable while valid
        ca2, cert2, _ = renewer.renew()
        assert ca1 == ca2 and cert1 == cert2
        # pair rotates inside the renewal window
        near_expiry = cert_expiry(cert1) - datetime.timedelta(days=1)
        _, cert3, _ = renewer.renew(now=near_expiry)
        assert cert3 != cert1


class TestLeaderElection:
    def test_lease_handover(self):
        from kyverno_tpu.controllers.leaderelection import LeaderElector
        client = FakeClient()
        a = LeaderElector(client, 'test-lease', identity='a')
        b = LeaderElector(client, 'test-lease', identity='b')
        assert a.try_acquire(now=100.0) is True
        assert b.try_acquire(now=101.0) is False
        # expiry hands over
        assert b.try_acquire(now=200.0) is True
        assert a.try_acquire(now=201.0) is False
        b.release()
        assert a.try_acquire(now=202.0) is True

    def test_acquire_is_compare_and_swap(self):
        """Two replicas racing on an expired lease must not both win
        (ADVICE r2: non-atomic read-modify-write split-brain)."""
        from kyverno_tpu.controllers.leaderelection import LeaderElector
        client = FakeClient()
        a = LeaderElector(client, 'test-lease', identity='a')
        b = LeaderElector(client, 'test-lease', identity='b')
        assert a.try_acquire(now=100.0) is True
        # b observes the expired lease, then a renews before b's update
        # lands: b's CAS must fail (conflict) and re-read a's fresh renew
        real_get = client.get_resource
        raced = []

        def racing_get(api, kind, ns, name, *args, **kw):
            lease = real_get(api, kind, ns, name, *args, **kw)
            if kind == 'Lease' and not raced:
                raced.append(True)
                a.try_acquire(now=200.0)  # a renews between b's read+write
            return lease
        client.get_resource = racing_get
        assert b.try_acquire(now=200.0) is False
        client.get_resource = real_get
        assert a.is_leader() and not b.is_leader()

    def test_renew_time_is_rfc3339_microtime(self):
        """coordination.k8s.io/v1 renewTime must interoperate with
        client-go holders (RFC3339 MicroTime, not an epoch float)."""
        from kyverno_tpu.controllers.leaderelection import (
            LeaderElector, _parse_microtime)
        client = FakeClient()
        a = LeaderElector(client, 'test-lease', identity='a')
        a.try_acquire(now=1753833600.125)
        lease = client.get_resource('coordination.k8s.io/v1', 'Lease',
                                    'kyverno', 'test-lease')
        renew = lease['spec']['renewTime']
        assert isinstance(renew, str) and renew.endswith('Z')
        assert 'T' in renew
        assert abs(_parse_microtime(renew) - 1753833600.125) < 1e-5
        # a client-go-style holder's value parses too
        assert _parse_microtime('2026-07-30T00:00:00.500000Z') > 0
        # legacy epoch floats remain readable
        assert _parse_microtime(100.5) == 100.5


class TestToggles:
    def test_env_and_flag_tiers(self, monkeypatch):
        from kyverno_tpu.config.toggle import Toggle
        t = Toggle(False, 'FLAG_X_TEST')
        assert t.enabled() is False
        monkeypatch.setenv('FLAG_X_TEST', 'true')
        assert t.enabled() is True
        t.parse('false')  # flag tier wins over env
        assert t.enabled() is False
        t.reset()
        assert t.enabled() is True

    def test_force_failure_policy_ignore(self, monkeypatch):
        from kyverno_tpu.api.policy import Policy
        from kyverno_tpu.controllers.webhook import WebhookConfigReconciler
        monkeypatch.setenv('FLAG_FORCE_FAILURE_POLICY_IGNORE', 'true')
        client = FakeClient()
        rec = WebhookConfigReconciler(client, b'ca', 'kyverno')
        pol = Policy(AUDIT_POLICY)
        rec.reconcile([pol])
        # the toggle governs the RESOURCE webhooks; the static policy
        # webhook keeps Fail (reference: controller.go:676 vs :569)
        cfg = client.get_resource(
            'admissionregistration.k8s.io/v1',
            'ValidatingWebhookConfiguration', '',
            'kyverno-resource-validating-webhook-cfg')
        hooks = cfg.get('webhooks', [])
        assert hooks and all(
            w.get('failurePolicy') == 'Ignore' for w in hooks)
