"""Device-vs-host equivalence for compiled foreach validate rules
(compiler foreach + mode-B conditions vs engine.py _validate_foreach)."""

import random

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

# the disallow-capabilities-strict shape from the reference restricted
# chart (charts/kyverno-policies/templates/restricted)
PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-drop-all
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: require-drop-all
      match: {any: [{resources: {kinds: [Pod]}}]}
      preconditions:
        all:
        - key: "{{ request.operation || 'BACKGROUND' }}"
          operator: NotEquals
          value: DELETE
      validate:
        message: Containers must drop `ALL` capabilities.
        foreach:
          - list: request.object.spec.[ephemeralContainers, initContainers, containers][]
            deny:
              conditions:
                all:
                - key: ALL
                  operator: AnyNotIn
                  value: "{{ element.securityContext.capabilities.drop[] || `[]` }}"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: adding-capabilities-strict
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: adding-capabilities-strict
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: Any capabilities added other than NET_BIND_SERVICE are disallowed.
        foreach:
          - list: request.object.spec.[ephemeralContainers, initContainers, containers][]
            deny:
              conditions:
                all:
                - key: "{{ element.securityContext.capabilities.add[] || `[]` }}"
                  operator: AnyNotIn
                  value:
                  - NET_BIND_SERVICE
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: foreach-precond
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: image-tags
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: named containers need tags
        foreach:
          - list: request.object.spec.containers
            preconditions:
              all:
                - key: "{{ element.name }}"
                  operator: NotEquals
                  value: skipme
            deny:
              conditions:
                any:
                  - key: "{{ element.image }}"
                    operator: Equals
                    value: "*:latest"
"""


def load_pack():
    return [Policy(d) for d in yaml.safe_load_all(PACK)]


_CAPS = ['ALL', 'NET_ADMIN', 'KILL', 'NET_BIND_SERVICE', 'CHOWN']


def make_pod(rng):
    def container(i):
        c = {'name': rng.choice([f'c{i}', 'skipme']),
             'image': rng.choice(['nginx:latest', 'nginx:1.25', 'app',
                                  'ghcr.io/a/b:latest'])}
        if rng.random() < 0.7:
            caps = {}
            if rng.random() < 0.8:
                caps['drop'] = rng.choice(
                    [['ALL'], [], ['KILL'], ['ALL', 'KILL'], ['all'], None])
            if rng.random() < 0.6:
                caps['add'] = rng.sample(_CAPS, rng.randint(0, 2))
            c['securityContext'] = {'capabilities': caps}
        elif rng.random() < 0.3:
            c['securityContext'] = {}
        return c
    spec = {'containers': [container(i)
                           for i in range(rng.randint(1, 3))]}
    if rng.random() < 0.3:
        spec['initContainers'] = [container(9)]
    if rng.random() < 0.2:
        spec['ephemeralContainers'] = [container(8)]
    if rng.random() < 0.05:
        del spec['containers']
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{rng.randint(0, 999)}',
                         'namespace': 'default'},
            'spec': spec}


class TestForEachCompile:
    def test_pack_fully_compiles(self):
        cps = compile_policies(load_pack())
        assert cps.host_rules == [], \
            [r.get('name') for _, r, _ in cps.host_rules]
        assert len(cps.programs) == 3

    def test_chart_restricted_strict_compiles(self):
        import os
        chart = '/root/reference/charts/kyverno-policies'
        if not os.path.isdir(chart):
            return
        from kyverno_tpu.utils.helmlite import load_chart_policies
        docs = load_chart_policies(chart, profiles=('restricted',))
        strict = [Policy(d) for d in docs
                  if d['metadata']['name'] == 'disallow-capabilities-strict']
        assert strict
        cps = compile_policies(strict)
        assert cps.host_rules == [], \
            [r.get('name') for _, r, _ in cps.host_rules]


class TestForEachEquivalence:
    def test_device_vs_host_fuzz(self):
        policies = load_pack()
        engine = Engine()
        rng = random.Random(31)
        resources = [make_pod(rng) for _ in range(150)]
        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)
        for resource, responses in zip(resources, scanned):
            host = {}
            for policy in policies:
                resp = engine.apply_background_checks(
                    PolicyContext(policy, new_resource=resource))
                if resp.policy_response.rules:
                    host[policy.name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            got = {}
            for resp in responses:
                if resp.policy_response.rules:
                    got[resp.policy_response.policy_name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            assert got == host, f'divergence on {resource}'


class TestNullContextSemantics:
    """The host Context strips null-valued map keys (RFC-7386 merge
    patch), so variables resolving to explicit nulls raise NotFound —
    the encoder must do the same (review regression)."""

    def _check(self, policies, resource):
        engine = Engine()
        scanner = BatchScanner(policies)
        [resp_list] = scanner.scan([resource])
        host = {}
        for policy in policies:
            resp = engine.apply_background_checks(
                PolicyContext(policy, new_resource=resource))
            host.update({(policy.name, r.name): (r.status, r.message)
                         for r in resp.policy_response.rules})
        got = {}
        for resp in resp_list:
            got.update({(resp.policy_response.policy_name, r.name):
                        (r.status, r.message)
                        for r in resp.policy_response.rules})
        assert got == host, (got, host)

    def test_explicit_null_element_key_is_error(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: t, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: r
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        foreach:
          - list: request.object.spec.containers
            deny:
              conditions:
                all:
                  - key: X
                    operator: AnyNotIn
                    value: "{{ element.tagstr }}"
"""))
        pod = {'apiVersion': 'v1', 'kind': 'Pod',
               'metadata': {'name': 'p', 'namespace': 'd'},
               'spec': {'containers': [
                   {'name': 'c', 'image': 'x', 'tagstr': None}]}}
        self._check([policy], pod)

    def test_explicit_null_rule_level_key_is_error(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: t2, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: r
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        deny:
          conditions:
            any:
              - key: "{{ request.object.spec.hostNetwork }}"
                operator: Equals
                value: true
"""))
        pod = {'apiVersion': 'v1', 'kind': 'Pod',
               'metadata': {'name': 'p', 'namespace': 'd'},
               'spec': {'hostNetwork': None,
                        'containers': [{'name': 'c', 'image': 'x'}]}}
        self._check([policy], pod)

    def test_whitespace_prefixed_json_value(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: t3, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: r
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        foreach:
          - list: request.object.spec.containers
            deny:
              conditions:
                all:
                  - key: X
                    operator: AnyIn
                    value: "{{ element.tagstr }}"
"""))
        for tag in (' ["X"]', '\t["X"]', '["X"]', '["Y"]', 'X', ' X'):
            pod = {'apiVersion': 'v1', 'kind': 'Pod',
                   'metadata': {'name': 'p', 'namespace': 'd'},
                   'spec': {'containers': [
                       {'name': 'c', 'image': 'x', 'tagstr': tag}]}}
            self._check([policy], pod)
