"""Real cosign signature cryptography (reference: pkg/cosign/cosign.go:63).

Hermetic fixtures: keys and a self-signed CA generated in-test (like
engine/k8smanifest's offline ECDSA verification). Every negative case is
a *cryptographically* invalid input — tampered signature bytes, wrong
key, wrong digest in the payload, identity mismatch, untrusted chain —
not a metadata mismatch.
"""

import base64
import datetime

import pytest

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from kyverno_tpu.cosign import cosign
from kyverno_tpu.registry.client import MockRegistryClient, RegistryError

DIGEST = 'sha256:' + 'ab' * 32
REF = 'ghcr.io/org/app:v1'


def ec_key():
    return ec.generate_private_key(ec.SECP256R1())


def pem_public(key) -> str:
    return key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo).decode()


def pem_cert(cert) -> str:
    return cert.public_bytes(serialization.Encoding.PEM).decode()


def make_ca(cn='test-ca'):
    key = ec_key()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime(2026, 1, 1)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key()).serial_number(1)
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return key, cert


def make_leaf(ca_key, ca_cert, email='dev@example.com',
              issuer_url='https://accounts.example.com'):
    key = ec_key()
    now = datetime.datetime(2026, 1, 1)
    builder = (x509.CertificateBuilder()
               .subject_name(x509.Name(
                   [x509.NameAttribute(NameOID.COMMON_NAME, 'signer')]))
               .issuer_name(ca_cert.subject)
               .public_key(key.public_key()).serial_number(2)
               .not_valid_before(now)
               .not_valid_after(now + datetime.timedelta(days=365))
               .add_extension(x509.SubjectAlternativeName(
                   [x509.RFC822Name(email)]), critical=False))
    if issuer_url:
        builder = builder.add_extension(
            x509.UnrecognizedExtension(
                x509.ObjectIdentifier('1.3.6.1.4.1.57264.1.1'),
                issuer_url.encode()), critical=False)
    return key, builder.sign(ca_key, hashes.SHA256())


def registry():
    r = MockRegistryClient()
    r.add_image(REF, DIGEST)
    return r


class TestKeyedVerification:
    def test_valid_signature_passes(self):
        key = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        r.add_signature(REF, cosign.signature_entry(key, payload))
        resp = cosign.verify_signature(
            r, cosign.Options(REF, key=pem_public(key)))
        assert resp.digest == DIGEST

    def test_tampered_signature_fails(self):
        key = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(key, payload)
        sig = bytearray(base64.b64decode(entry['signature']))
        sig[-1] ^= 0xFF
        entry['signature'] = base64.b64encode(bytes(sig)).decode()
        r.add_signature(REF, entry)
        with pytest.raises(RegistryError, match='verification failed'):
            cosign.verify_signature(
                r, cosign.Options(REF, key=pem_public(key)))

    def test_tampered_payload_fails(self):
        key = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(key, payload)
        entry['payload'] = base64.b64encode(
            cosign.make_payload(REF, 'sha256:' + 'cd' * 32)).decode()
        r.add_signature(REF, entry)
        with pytest.raises(RegistryError, match='verification failed'):
            cosign.verify_signature(
                r, cosign.Options(REF, key=pem_public(key)))

    def test_wrong_key_fails(self):
        key, other = ec_key(), ec_key()
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            key, cosign.make_payload(REF, DIGEST)))
        with pytest.raises(RegistryError):
            cosign.verify_signature(
                r, cosign.Options(REF, key=pem_public(other)))

    def test_wrong_digest_in_payload_fails(self):
        key = ec_key()
        r = registry()
        # correctly signed payload claiming a DIFFERENT image digest
        payload = cosign.make_payload(REF, 'sha256:' + 'cd' * 32)
        r.add_signature(REF, cosign.signature_entry(key, payload))
        with pytest.raises(RegistryError, match='does not match image'):
            cosign.verify_signature(
                r, cosign.Options(REF, key=pem_public(key)))

    def test_pem_attestor_rejects_legacy_metadata_entries(self):
        key = ec_key()
        r = registry()
        r.sign(REF, 'legacy-id')  # metadata-only entry, no crypto
        with pytest.raises(RegistryError):
            cosign.verify_signature(
                r, cosign.Options(REF, key=pem_public(key)))

    def test_annotations_checked(self):
        key = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST, {'env': 'prod'})
        r.add_signature(REF, cosign.signature_entry(key, payload))
        assert cosign.verify_signature(r, cosign.Options(
            REF, key=pem_public(key), annotations={'env': 'prod'})).digest
        with pytest.raises(RegistryError, match='annotation'):
            cosign.verify_signature(r, cosign.Options(
                REF, key=pem_public(key), annotations={'env': 'dev'}))


class TestKeylessVerification:
    def test_chain_and_identity_pass(self):
        ca_key, ca_cert = make_ca()
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        r.add_signature(REF, cosign.signature_entry(
            leaf_key, payload, cert_pem=pem_cert(leaf_cert)))
        resp = cosign.verify_signature(r, cosign.Options(
            REF, roots=pem_cert(ca_cert), subject='dev@example.com',
            issuer='https://accounts.example.com'))
        assert resp.digest == DIGEST

    def test_subject_wildcard(self):
        ca_key, ca_cert = make_ca()
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            leaf_key, cosign.make_payload(REF, DIGEST),
            cert_pem=pem_cert(leaf_cert)))
        assert cosign.verify_signature(r, cosign.Options(
            REF, roots=pem_cert(ca_cert),
            subject='*@example.com')).digest == DIGEST

    def test_identity_mismatch_fails(self):
        ca_key, ca_cert = make_ca()
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            leaf_key, cosign.make_payload(REF, DIGEST),
            cert_pem=pem_cert(leaf_cert)))
        with pytest.raises(RegistryError, match='subject'):
            cosign.verify_signature(r, cosign.Options(
                REF, roots=pem_cert(ca_cert),
                subject='other@example.com'))
        with pytest.raises(RegistryError, match='issuer'):
            cosign.verify_signature(r, cosign.Options(
                REF, roots=pem_cert(ca_cert),
                issuer='https://evil.example.com'))

    def test_untrusted_ca_fails(self):
        ca_key, ca_cert = make_ca()
        other_ca_key, other_ca_cert = make_ca('other-ca')
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            leaf_key, cosign.make_payload(REF, DIGEST),
            cert_pem=pem_cert(leaf_cert)))
        with pytest.raises(RegistryError, match='chain'):
            cosign.verify_signature(r, cosign.Options(
                REF, roots=pem_cert(other_ca_cert)))

    def test_intermediate_chain(self):
        root_key, root_cert = make_ca('root')
        int_key = ec_key()
        now = datetime.datetime(2026, 1, 1)
        int_name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, 'intermediate')])
        int_cert = (x509.CertificateBuilder()
                    .subject_name(int_name).issuer_name(root_cert.subject)
                    .public_key(int_key.public_key()).serial_number(3)
                    .not_valid_before(now)
                    .not_valid_after(now + datetime.timedelta(days=730))
                    .add_extension(
                        x509.BasicConstraints(ca=True, path_length=0),
                        critical=True)
                    .sign(root_key, hashes.SHA256()))
        leaf_key, leaf_cert = make_leaf(int_key, int_cert)
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            leaf_key, cosign.make_payload(REF, DIGEST),
            cert_pem=pem_cert(leaf_cert), chain_pem=pem_cert(int_cert)))
        assert cosign.verify_signature(r, cosign.Options(
            REF, roots=pem_cert(root_cert))).digest == DIGEST


class TestPinnedCert:
    def test_pinned_cert_ignores_entry_cert(self):
        """With a pinned attestor cert, an attacker-supplied entry cert
        must never be the verification key."""
        ca_key, ca_cert = make_ca()
        pinned_key, pinned_cert = make_leaf(ca_key, ca_cert)
        evil_key, evil_cert = make_leaf(*make_ca('evil'),
                                        email='dev@example.com')
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        # entry signed by the ATTACKER's key, carrying the attacker cert
        r.add_signature(REF, cosign.signature_entry(
            evil_key, payload, cert_pem=pem_cert(evil_cert)))
        with pytest.raises(RegistryError, match='verification failed'):
            cosign.verify_signature(r, cosign.Options(
                REF, cert=pem_cert(pinned_cert)))
        # the genuine pinned-key signature passes
        r.add_signature(REF, cosign.signature_entry(pinned_key, payload))
        assert cosign.verify_signature(r, cosign.Options(
            REF, cert=pem_cert(pinned_cert))).digest == DIGEST

    def test_keyless_without_roots_rejected(self):
        key, cert = make_leaf(*make_ca())
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            key, cosign.make_payload(REF, DIGEST),
            cert_pem=pem_cert(cert)))
        with pytest.raises(RegistryError, match='requires roots'):
            cosign.verify_signature(r, cosign.Options(
                REF, subject='dev@example.com'))

    def test_attestation_keyless_without_roots_dropped(self):
        import json as _json
        key, cert = make_leaf(*make_ca())
        payload = _json.dumps({'predicateType': 'x'}).encode()
        r = registry()
        r.add_attestation(REF, {
            'payload': base64.b64encode(payload).decode(),
            'signature': base64.b64encode(
                cosign.sign_payload(key, payload)).decode(),
            'cert': pem_cert(cert)})
        resp = cosign.fetch_attestations(
            r, cosign.Options(REF, subject='dev@example.com'))
        assert resp.statements == []

    def test_malformed_entry_cert_skips_to_valid_entry(self):
        key = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        bad = cosign.signature_entry(key, payload)
        bad['cert'] = ('-----BEGIN CERTIFICATE-----\ngarbage\n'
                       '-----END CERTIFICATE-----\n')
        ca_key, ca_cert = make_ca()
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        good = cosign.signature_entry(leaf_key, payload,
                                      cert_pem=pem_cert(leaf_cert))
        r.add_signature(REF, bad)
        r.add_signature(REF, good)
        assert cosign.verify_signature(r, cosign.Options(
            REF, roots=pem_cert(ca_cert))).digest == DIGEST


class TestEngineIntegration:
    """verifyImages rules with PEM-keyed attestors run real crypto
    (reference: pkg/engine/imageVerify.go:69 VerifyAndPatchImages)."""

    def _policy(self, key_pem):
        from kyverno_tpu.api.policy import Policy
        return Policy({
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 'verify', 'annotations': {
                'pod-policies.kyverno.io/autogen-controllers': 'none'}},
            'spec': {'rules': [{
                'name': 'check-sig',
                'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                'verifyImages': [{
                    'imageReferences': ['ghcr.io/org/*'],
                    'attestors': [{'entries': [
                        {'keys': {'publicKeys': key_pem}}]}],
                    'mutateDigest': True,
                }]}]}})

    def _pod(self):
        return {'apiVersion': 'v1', 'kind': 'Pod',
                'metadata': {'name': 'p', 'namespace': 'd'},
                'spec': {'containers': [{'name': 'c', 'image': REF}]}}

    def test_real_key_pass_and_fail(self):
        from kyverno_tpu.engine.api import PolicyContext, RuleStatus
        from kyverno_tpu.engine.engine import Engine
        key = ec_key()
        r = registry()
        r.add_signature(REF, cosign.signature_entry(
            key, cosign.make_payload(REF, DIGEST)))
        engine = Engine()
        pctx = PolicyContext(self._policy(pem_public(key)),
                             new_resource=self._pod())
        er, _ = engine.verify_and_patch_images(pctx, r)
        assert er.policy_response.rules[0].status == RuleStatus.PASS
        # unsigned image with a different (real) key must fail
        pctx2 = PolicyContext(self._policy(pem_public(ec_key())),
                              new_resource=self._pod())
        er2, _ = engine.verify_and_patch_images(pctx2, r)
        assert er2.policy_response.rules[0].status == RuleStatus.FAIL


class TestAttestationCrypto:
    def test_signed_statement_verifies(self):
        key = ec_key()
        r = registry()
        import json
        stmt = {'_type': 'https://in-toto.io/Statement/v0.1',
                'predicateType': 'https://slsa.dev/provenance/v0.2',
                'predicate': {'builder': {'id': 'gh-actions'}}}
        payload = json.dumps(stmt).encode()
        r.add_attestation(REF, {
            'payload': base64.b64encode(payload).decode(),
            'signature': base64.b64encode(
                cosign.sign_payload(key, payload)).decode()})
        resp = cosign.fetch_attestations(
            r, cosign.Options(REF, key=pem_public(key)))
        assert resp.statements == [stmt]

    def test_bad_attestation_signature_dropped(self):
        key, other = ec_key(), ec_key()
        r = registry()
        import json
        payload = json.dumps({'predicateType': 'x'}).encode()
        r.add_attestation(REF, {
            'payload': base64.b64encode(payload).decode(),
            'signature': base64.b64encode(
                cosign.sign_payload(other, payload)).decode()})
        resp = cosign.fetch_attestations(
            r, cosign.Options(REF, key=pem_public(key)))
        assert resp.statements == []


class TestRekorTlog:
    """Offline Rekor bundle verification (reference engages the cosign
    library's tlog path through pkg/cosign/cosign.go:204; the CRD says
    'If the value is nil, Rekor is not checked' —
    image_verification_types.go:149)."""

    def _signed_entry(self, key, rekor_key, kind='hashedrekord',
                      integrated_time=None):
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(key, payload)
        entry['bundle'] = cosign.make_bundle(
            rekor_key, payload, base64.b64decode(entry['signature']),
            kind=kind, integrated_time=integrated_time)
        return entry

    def test_valid_bundle_accepts(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        r.add_signature(REF, self._signed_entry(key, rekor))
        resp = cosign.verify_signature(r, cosign.Options(
            REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))
        assert resp.digest == DIGEST

    def test_valid_rekord_bundle_accepts(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        r.add_signature(REF, self._signed_entry(key, rekor, kind='rekord'))
        resp = cosign.verify_signature(r, cosign.Options(
            REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))
        assert resp.digest == DIGEST

    def test_missing_bundle_rejects_when_rekor_configured(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        r.add_signature(REF, cosign.signature_entry(key, payload))
        with pytest.raises(RegistryError, match='bundle'):
            cosign.verify_signature(r, cosign.Options(
                REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))

    def test_no_rekor_block_means_not_checked(self):
        key = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        r.add_signature(REF, cosign.signature_entry(key, payload))
        resp = cosign.verify_signature(
            r, cosign.Options(REF, key=pem_public(key)))
        assert resp.digest == DIGEST

    def test_ignore_tlog_skips_bundle_requirement(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        r.add_signature(REF, cosign.signature_entry(key, payload))
        resp = cosign.verify_signature(r, cosign.Options(
            REF, key=pem_public(key), rekor_pubkey=pem_public(rekor),
            ignore_tlog=True))
        assert resp.digest == DIGEST

    def test_set_signed_by_wrong_key_rejects(self):
        key, rekor, impostor = ec_key(), ec_key(), ec_key()
        r = registry()
        r.add_signature(REF, self._signed_entry(key, impostor))
        with pytest.raises(RegistryError, match='signature verification'):
            cosign.verify_signature(r, cosign.Options(
                REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))

    def test_tampered_set_payload_rejects(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        entry = self._signed_entry(key, rekor)
        entry['bundle']['Payload']['logIndex'] += 1
        r.add_signature(REF, entry)
        with pytest.raises(RegistryError):
            cosign.verify_signature(r, cosign.Options(
                REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))

    def test_body_hash_mismatch_rejects(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(key, payload)
        # bundle built over a DIFFERENT payload: SET verifies but the
        # entry does not describe this signature's payload
        other = cosign.make_payload(REF, 'sha256:' + 'cd' * 32)
        other_entry = cosign.signature_entry(key, other)
        entry['bundle'] = cosign.make_bundle(
            rekor, other, base64.b64decode(other_entry['signature']))
        r.add_signature(REF, entry)
        with pytest.raises(RegistryError):
            cosign.verify_signature(r, cosign.Options(
                REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))

    def test_bundle_signature_mismatch_rejects(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(key, payload)
        # entry hash matches the payload but the logged signature bytes
        # belong to a different signing event
        entry['bundle'] = cosign.make_bundle(
            rekor, payload, cosign.sign_payload(ec_key(), payload))
        r.add_signature(REF, entry)
        with pytest.raises(RegistryError, match='does not match'):
            cosign.verify_signature(r, cosign.Options(
                REF, key=pem_public(key), rekor_pubkey=pem_public(rekor)))

    def test_keyless_integrated_time_outside_cert_validity_rejects(self):
        ca_key, ca_cert = make_ca()
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        rekor = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(leaf_key, payload,
                                       cert_pem=pem_cert(leaf_cert))
        # leaf valid [2026-01-01, +365d]; integrate before the window
        before = int(datetime.datetime(
            2025, 6, 1, tzinfo=datetime.timezone.utc).timestamp())
        entry['bundle'] = cosign.make_bundle(
            rekor, payload, base64.b64decode(entry['signature']),
            integrated_time=before)
        r.add_signature(REF, entry)
        with pytest.raises(RegistryError, match='validity'):
            cosign.verify_signature(r, cosign.Options(
                REF, roots=pem_cert(ca_cert),
                rekor_pubkey=pem_public(rekor)))

    def test_keyless_integrated_time_inside_cert_validity_accepts(self):
        ca_key, ca_cert = make_ca()
        leaf_key, leaf_cert = make_leaf(ca_key, ca_cert)
        rekor = ec_key()
        r = registry()
        payload = cosign.make_payload(REF, DIGEST)
        entry = cosign.signature_entry(leaf_key, payload,
                                       cert_pem=pem_cert(leaf_cert))
        inside = int(datetime.datetime(
            2026, 6, 1, tzinfo=datetime.timezone.utc).timestamp())
        entry['bundle'] = cosign.make_bundle(
            rekor, payload, base64.b64decode(entry['signature']),
            integrated_time=inside)
        r.add_signature(REF, entry)
        resp = cosign.verify_signature(r, cosign.Options(
            REF, roots=pem_cert(ca_cert), rekor_pubkey=pem_public(rekor)))
        assert resp.digest == DIGEST

    def test_env_var_rekor_key(self, monkeypatch):
        key, rekor = ec_key(), ec_key()
        monkeypatch.setenv('SIGSTORE_REKOR_PUBLIC_KEY', pem_public(rekor))
        r = registry()
        r.add_signature(REF, self._signed_entry(key, rekor))
        resp = cosign.verify_signature(r, cosign.Options(
            REF, key=pem_public(key), rekor_url='https://rekor.internal'))
        assert resp.digest == DIGEST

    def test_attestations_respect_tlog(self):
        key, rekor = ec_key(), ec_key()
        r = registry()
        statement = {'_type': 'https://in-toto.io/Statement/v0.1',
                     'predicateType': 'https://example.com/provenance',
                     'predicate': {'ok': True}}
        import json as _json
        payload = _json.dumps(statement).encode()
        entry = cosign.signature_entry(key, payload)
        r.add_attestation(REF, entry)
        # no bundle + rekor configured -> statement filtered out
        resp = cosign.fetch_attestations(r, cosign.Options(
            REF, key=pem_public(key), rekor_pubkey=pem_public(rekor),
            fetch_attestations=True))
        assert resp.statements == []
        entry2 = dict(entry)
        entry2['bundle'] = cosign.make_bundle(
            rekor, payload, base64.b64decode(entry['signature']))
        r2 = registry()
        r2.add_attestation(REF, entry2)
        resp = cosign.fetch_attestations(r2, cosign.Options(
            REF, key=pem_public(key), rekor_pubkey=pem_public(rekor),
            fetch_attestations=True))
        assert len(resp.statements) == 1
