"""BASELINE.md configs 4 and 5, scaled down for CI.

Config 4: JMESPath-heavy precondition/deny policies — device-vs-host
differential over a mixed pod population, and a floor on how much of
the pack actually compiles to device (the point of the workload).

Config 5: mutate + generate with foreach over a resource dump via
``BatchApplier`` — serial vs process-pool equality, cumulative mutation
semantics vs the engine loop, and the generate URs feeding the real
background controller.
"""

import random

import pytest

import bench
from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.apply import BatchApplier
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine


class TestConfig4JMESPathHeavy:
    @pytest.fixture(scope='class')
    def policies(self):
        return load_policies_from_yaml(bench.CONFIG4_PACK)

    @pytest.fixture(scope='class')
    def pods(self):
        rng = random.Random(7)
        return [bench.make_config4_pod(rng, i) for i in range(160)]

    def test_pack_mostly_compiles(self, policies):
        scanner = BatchScanner(policies)
        n_rules = sum(len(p.rules) for p in policies)
        # the workload exists to exercise device-compiled JMESPath
        # conditions; host fallback for most rules would defeat it
        assert len(scanner.cps.programs) >= n_rules - 1, \
            [(r, err) for _, r, err in scanner.cps.host_rules]

    def test_device_matches_host(self, policies, pods):
        scanner = BatchScanner(policies)
        device = scanner.scan(pods)
        engine = Engine()
        for doc, responses in zip(pods, device):
            by_policy = {r.policy_response.policy_name: r
                         for r in responses}
            for policy in policies:
                host = engine.apply_background_checks(
                    PolicyContext(policy, new_resource=doc))
                dev = by_policy.get(policy.name)
                host_rules = [(r.name, r.status, r.message)
                              for r in host.policy_response.rules]
                dev_rules = [(r.name, r.status, r.message)
                             for r in dev.policy_response.rules] \
                    if dev is not None else []
                assert dev_rules == host_rules, \
                    f'{policy.name} diverged on {doc["metadata"]["name"]}'

    def test_verdict_mix_is_nontrivial(self, policies, pods):
        """The synthetic population must actually trip the JMESPath
        conditions both ways, or the bench measures nothing."""
        scanner = BatchScanner(policies)
        out = scanner.scan(pods)
        statuses = {str(r.status) for rs in out
                    for r in rs for r in r.policy_response.rules}
        assert 'pass' in statuses and 'fail' in statuses and \
            'skip' in statuses


class TestConfig5MutateGenerate:
    @pytest.fixture(scope='class')
    def policies(self):
        return load_policies_from_yaml(bench.CONFIG5_PACK)

    @pytest.fixture(scope='class')
    def dump(self):
        rng = random.Random(11)
        return [bench.make_config5_resource(rng, i) for i in range(300)]

    def test_applier_matches_engine_loop(self, policies, dump):
        applier = BatchApplier(policies, processes=0)
        results = applier.apply(dump)
        engine = Engine()
        for doc, result in zip(dump, results):
            patched = doc
            for policy in applier.mutate_policies:
                ctx = PolicyContext(policy, new_resource=patched)
                resp = engine.mutate(ctx)
                if resp.patched_resource is not None:
                    patched = resp.patched_resource
            assert result.patched == patched

    def test_parallel_matches_serial(self, policies, dump):
        applier = BatchApplier(policies, processes=2)
        serial = applier.apply(dump, parallel=False)
        par = applier.apply(dump, parallel=True)
        for s, p in zip(serial, par):
            assert s.patched == p.patched
            assert s.rule_results == p.rule_results
            assert s.ur_specs == p.ur_specs

    def test_mutations_applied(self, policies, dump):
        applier = BatchApplier(policies, processes=0)
        results = applier.apply(dump)
        pods = [(d, r) for d, r in zip(dump, results)
                if d.get('kind') == 'Pod']
        assert pods
        for doc, r in pods:
            labels = r.patched['metadata'].get('labels') or {}
            assert labels.get('managed') == 'true'
            anns = r.patched['metadata'].get('annotations') or {}
            assert anns.get('policy.io/revision') == 'r1'
            for cont in r.patched['spec']['containers']:
                assert cont.get('imagePullPolicy') in \
                    ('IfNotPresent', 'Always')

    def test_foreach_preserves_existing_pull_policy(self, policies):
        doc = {'apiVersion': 'v1', 'kind': 'Pod',
               'metadata': {'name': 'p', 'namespace': 'default'},
               'spec': {'containers': [
                   {'name': 'a', 'image': 'nginx:1',
                    'imagePullPolicy': 'Always'},
                   {'name': 'b', 'image': 'redis:7'}]}}
        applier = BatchApplier(policies, processes=0)
        [r] = applier.apply([doc])
        conts = {c['name']: c for c in r.patched['spec']['containers']}
        assert conts['a']['imagePullPolicy'] == 'Always'
        assert conts['b']['imagePullPolicy'] == 'IfNotPresent'

    def test_generate_urs_feed_background_pipeline(self, policies, dump):
        from kyverno_tpu.background.update_request_controller import \
            UpdateRequestController
        from kyverno_tpu.background.updaterequest import \
            UpdateRequestGenerator
        from kyverno_tpu.dclient.client import FakeClient
        applier = BatchApplier(policies, processes=0)
        results = applier.apply(dump)
        ur_specs = [s for r in results for s in r.ur_specs]
        namespaces = [d for d in dump if d.get('kind') == 'Namespace']
        assert len(ur_specs) == len(namespaces) > 0
        client = FakeClient()
        for ns in namespaces:
            client.create_resource('v1', 'Namespace', '', ns)
        by_name = {p.name: p for p in policies}
        ctrl = UpdateRequestController(client, Engine(),
                                       policy_getter=by_name.get)
        gen = UpdateRequestGenerator(client)
        for spec in ur_specs:
            gen.apply(spec)
        ctrl.process_pending()
        netpols = client.list_resource('networking.k8s.io/v1',
                                       'NetworkPolicy')
        assert len(netpols) == len(namespaces)
        for np_ in netpols:
            assert np_['spec']['policyTypes'] == ['Ingress', 'Egress']
