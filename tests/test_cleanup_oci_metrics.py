"""CronJob-based cleanup contract, CLI oci push/pull, and the policy
metrics controller (reference: pkg/controllers/cleanup/controller.go:164,
cmd/cli/kubectl-kyverno/oci, pkg/controllers/metrics/policy)."""

import re
import json
import urllib.request

import yaml

from kyverno_tpu.cmd.cleanup_controller import CleanupDaemon
from kyverno_tpu.cmd.internal import Setup
from kyverno_tpu.controllers.cleanup import CleanupController
from kyverno_tpu.controllers.policymetrics import (POLICY_RULE_INFO,
                                                  PolicyMetricsController)
from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.observability.metrics import (POLICY_CHANGES,
                                               MetricsRegistry)

CLEANUP_POLICY = {
    'apiVersion': 'kyverno.io/v2alpha1', 'kind': 'ClusterCleanupPolicy',
    'metadata': {'name': 'sweep-temps', 'uid': 'u-123'},
    'spec': {
        'schedule': '*/5 * * * *',
        'match': {'any': [{'resources': {
            'kinds': ['ConfigMap'],
            'selector': {'matchLabels': {'temp': 'true'}}}}]},
    }}


class TestCleanupCronJobs:
    def test_cronjob_reconciled(self):
        client = FakeClient()
        ctrl = CleanupController(client)
        ctrl.set_policy(CLEANUP_POLICY)
        [cj] = ctrl.reconcile_cronjobs('kyverno')
        assert cj['kind'] == 'CronJob'
        assert cj['spec']['schedule'] == '*/5 * * * *'
        assert cj['spec']['concurrencyPolicy'] == 'Forbid'
        [owner] = cj['metadata']['ownerReferences']
        assert owner['kind'] == 'ClusterCleanupPolicy'
        assert owner['name'] == 'sweep-temps'
        args = cj['spec']['jobTemplate']['spec']['template']['spec'][
            'containers'][0]['args']
        assert any('/cleanup?policy=sweep-temps' in a for a in args)
        # stored in the fake cluster
        stored = client.list_resource('batch/v1', 'CronJob', 'kyverno',
                                      None)
        [name] = [c['metadata']['name'] for c in stored]
        # name = prefix + 8-hex digest of kind/key (collision-free for
        # e.g. ClusterCleanupPolicy 'a-b' vs CleanupPolicy a/b)
        assert re.fullmatch(r'cleanup-sweep-temps-[0-9a-f]{8}', name)

    def test_stale_cronjob_removed(self):
        client = FakeClient()
        ctrl = CleanupController(client)
        ctrl.set_policy(CLEANUP_POLICY)
        ctrl.reconcile_cronjobs('kyverno')
        ctrl.delete_policy(CLEANUP_POLICY)
        ctrl.reconcile_cronjobs('kyverno')
        assert client.list_resource('batch/v1', 'CronJob', 'kyverno',
                                    None) == []

    def test_cleanup_http_endpoint(self):
        client = FakeClient()
        client.create_resource('kyverno.io/v2alpha1',
                               'ClusterCleanupPolicy', '', CLEANUP_POLICY)
        client.create_resource('v1', 'ConfigMap', 'default', {
            'apiVersion': 'v1', 'kind': 'ConfigMap',
            'metadata': {'name': 'tmp', 'namespace': 'default',
                         'labels': {'temp': 'true'}}})
        setup = Setup('cleanup', args=[])
        setup.client = client
        daemon = CleanupDaemon(setup)
        daemon.sync_policies()
        port = daemon.server.start()
        try:
            body = urllib.request.urlopen(
                f'http://127.0.0.1:{port}/cleanup?policy=sweep-temps'
            ).read().decode()
            assert 'cleaned 1 resources' in body
            assert client.list_resource('v1', 'ConfigMap', 'default',
                                        None) == []
            # unknown policy → 404
            try:
                urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/cleanup?policy=nope')
                raise AssertionError('expected 404')
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            daemon.server.stop()


class TestOCI:
    POLICY_YAML = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: require-labels}
spec:
  rules:
    - name: check-app
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: app label required
        pattern: {metadata: {labels: {app: "?*"}}}
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: disallow-latest}
spec:
  rules:
    - name: no-latest
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: no latest tag
        pattern: {spec: {containers: [{image: "!*:latest"}]}}
"""

    def test_push_pull_roundtrip(self, tmp_path):
        src = tmp_path / 'policies.yaml'
        src.write_text(self.POLICY_YAML)
        store = tmp_path / 'store'
        from kyverno_tpu.cli import oci_command
        digest = oci_command.push([str(src)], f'{store}:v1')
        assert digest.startswith('sha256:')
        # standard OCI layout on disk
        assert (store / 'oci-layout').exists()
        assert (store / 'index.json').exists()
        out = tmp_path / 'out'
        written = oci_command.pull(f'{store}:v1', str(out))
        assert sorted(p.rsplit('/', 1)[-1] for p in written) == \
            ['disallow-latest.yaml', 'require-labels.yaml']
        docs = [yaml.safe_load(open(p)) for p in written]
        originals = list(yaml.safe_load_all(self.POLICY_YAML))
        assert sorted(d['metadata']['name'] for d in docs) == \
            sorted(d['metadata']['name'] for d in originals)
        # bit-exact policy documents round-trip
        by_name = {d['metadata']['name']: d for d in docs}
        for orig in originals:
            assert by_name[orig['metadata']['name']] == orig

    def test_cli_entrypoint(self, tmp_path, capsys):
        src = tmp_path / 'p.yaml'
        src.write_text(self.POLICY_YAML)
        from kyverno_tpu.cli.main import main
        assert main(['oci', 'push', str(src),
                     '-i', f'{tmp_path}/store:latest']) == 0
        assert main(['oci', 'pull', '-i', f'{tmp_path}/store:latest',
                     '-o', str(tmp_path / 'pulled')]) == 0
        out = capsys.readouterr().out
        assert 'pushed' in out and 'pulled 2 policies' in out

    def test_blob_corruption_detected(self, tmp_path):
        import os
        src = tmp_path / 'p.yaml'
        src.write_text(self.POLICY_YAML)
        store = str(tmp_path / 'store')
        from kyverno_tpu.cli import oci_command
        oci_command.push([str(src)], f'{store}:v1')
        blobs_dir = os.path.join(store, 'blobs', 'sha256')
        victim = sorted(os.listdir(blobs_dir))[0]
        with open(os.path.join(blobs_dir, victim), 'ab') as f:
            f.write(b'tampered')
        import pytest
        with pytest.raises(ValueError, match='corrupted'):
            oci_command.pull(f'{store}:v1', str(tmp_path / 'out'))


POLICY_DOC = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'metered'},
    'spec': {'validationFailureAction': 'Enforce', 'rules': [
        {'name': 'r1',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'm',
                      'pattern': {'metadata': {'name': '?*'}}}},
        {'name': 'r2',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'mutate': {'patchStrategicMerge': {'metadata': {'labels': {
             'x': 'y'}}}}},
    ]}}


class TestPolicyMetrics:
    def test_policy_events_move_instruments(self):
        client = FakeClient()
        registry = MetricsRegistry()
        PolicyMetricsController(client, registry)

        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               POLICY_DOC)
        assert registry.counter_value(
            POLICY_CHANGES, policy_change_type='created',
            policy_name='metered', policy_namespace='-',
            policy_type='cluster', policy_validation_mode='enforce',
            policy_background_mode='true') == 1
        assert registry.gauge_total(POLICY_RULE_INFO) == 2

        updated = json.loads(json.dumps(POLICY_DOC))
        updated['spec']['rules'] = updated['spec']['rules'][:1]
        client.update_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               updated)
        # rule gauge re-derived: r2 retracted
        assert registry.gauge_total(POLICY_RULE_INFO) == 1

        client.delete_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               'metered')
        assert registry.gauge_total(POLICY_RULE_INFO) == 0
        assert registry.counter_total(POLICY_CHANGES) == 3
        # rendered exposition includes the gauge type
        assert 'kyverno_policy_changes_total' in registry.render()

    def test_rule_types_labeled(self):
        client = FakeClient()
        registry = MetricsRegistry()
        PolicyMetricsController(client, registry)
        client.create_resource('kyverno.io/v1', 'ClusterPolicy', '',
                               POLICY_DOC)
        assert registry.gauge_value(
            POLICY_RULE_INFO, policy_name='metered',
            policy_namespace='-', policy_type='cluster',
            policy_validation_mode='enforce',
            policy_background_mode='true', rule_name='r1',
            rule_type='validate') == 1
        assert registry.gauge_value(
            POLICY_RULE_INFO, policy_name='metered',
            policy_namespace='-', policy_type='cluster',
            policy_validation_mode='enforce',
            policy_background_mode='true', rule_name='r2',
            rule_type='mutate') == 1
