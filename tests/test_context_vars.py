import pytest

from kyverno_tpu.engine.context import Context, merge_patch
from kyverno_tpu.engine import variables as vars_mod
from kyverno_tpu.engine import operators as ops
from kyverno_tpu.engine.variables import SubstitutionError


class TestMergePatch:
    def test_merge_objects(self):
        assert merge_patch({'a': {'x': 1}}, {'a': {'y': 2}}) == {'a': {'x': 1, 'y': 2}}

    def test_null_deletes(self):
        assert merge_patch({'a': 1, 'b': 2}, {'a': None}) == {'b': 2}

    def test_replace_non_objects(self):
        assert merge_patch({'a': [1, 2]}, {'a': [3]}) == {'a': [3]}


class TestContext:
    def test_add_resource_and_query(self):
        ctx = Context()
        ctx.add_resource({'metadata': {'name': 'pod-1'}})
        assert ctx.query('request.object.metadata.name') == 'pod-1'

    def test_checkpoint_restore(self):
        ctx = Context()
        ctx.add_variable('x', 1)
        ctx.checkpoint()
        ctx.add_variable('x', 2)
        assert ctx.query('x') == 2
        ctx.restore()
        assert ctx.query('x') == 1

    def test_reset_keeps_checkpoint(self):
        ctx = Context()
        ctx.add_variable('x', 1)
        ctx.checkpoint()
        ctx.add_variable('x', 2)
        ctx.reset()
        assert ctx.query('x') == 1
        ctx.add_variable('x', 3)
        ctx.restore()
        assert ctx.query('x') == 1

    def test_add_element_nesting(self):
        ctx = Context()
        ctx.add_element({'image': 'nginx'}, 0, 0)
        assert ctx.query('element.image') == 'nginx'
        assert ctx.query('elementIndex') == 0
        assert ctx.query('element0.image') == 'nginx'

    def test_service_account(self):
        ctx = Context()
        ctx.add_service_account('system:serviceaccount:kube-system:builder')
        assert ctx.query('serviceAccountName') == 'builder'
        assert ctx.query('serviceAccountNamespace') == 'kube-system'

    def test_has_changed(self):
        ctx = Context()
        ctx.add_resource({'spec': {'replicas': 2}})
        ctx.add_old_resource({'spec': {'replicas': 1}})
        assert ctx.has_changed('spec.replicas') is True
        ctx2 = Context()
        ctx2.add_resource({'spec': {'replicas': 1}})
        ctx2.add_old_resource({'spec': {'replicas': 1}})
        assert ctx2.has_changed('spec.replicas') is False


class TestSubstitution:
    def make_ctx(self):
        ctx = Context()
        ctx.add_resource({
            'metadata': {'name': 'web', 'namespace': 'apps',
                         'labels': {'app': 'web'}},
            'spec': {'replicas': 3},
        })
        return ctx

    def test_whole_leaf_variable_returns_raw(self):
        ctx = self.make_ctx()
        out = vars_mod.substitute_all(ctx, {'v': '{{request.object.spec.replicas}}'})
        assert out == {'v': 3}

    def test_string_splice(self):
        ctx = self.make_ctx()
        out = vars_mod.substitute_all(
            ctx, {'msg': 'name is {{request.object.metadata.name}}!'})
        assert out == {'msg': 'name is web!'}

    def test_multiple_vars(self):
        ctx = self.make_ctx()
        out = vars_mod.substitute_all(
            ctx, 'ns={{request.object.metadata.namespace}} app={{request.object.metadata.labels.app}}')
        assert out == 'ns=apps app=web'

    def test_escaped_variable(self):
        ctx = self.make_ctx()
        out = vars_mod.substitute_all(ctx, {'v': r'\{{ not a var }}'})
        assert out == {'v': '{{ not a var }}'}

    def test_non_string_splice_is_json(self):
        ctx = self.make_ctx()
        out = vars_mod.substitute_all(
            ctx, 'labels={{request.object.metadata.labels}}')
        assert out == 'labels={"app":"web"}'

    def test_nested_variable_resolution(self):
        ctx = self.make_ctx()
        ctx.add_variable('inner', 'metadata.name')
        out = vars_mod.substitute_all(ctx, '{{request.object.{{inner}}}}')
        assert out == 'web'

    def test_unresolved_variable_raises(self):
        ctx = self.make_ctx()
        with pytest.raises(SubstitutionError):
            vars_mod.substitute_all(ctx, '{{unknown!!!bad}}')

    def test_substitute_in_map_keys(self):
        ctx = self.make_ctx()
        out = vars_mod.substitute_all(
            ctx, {'{{request.object.metadata.name}}-suffix': 1})
        assert out == {'web-suffix': 1}

    def test_reference_substitution(self):
        doc = {'pattern': {'spec': {'replicas': '$(./../minReplicas)',
                                    'minReplicas': '2'}}}
        out = vars_mod.substitute_references(doc)
        assert out['pattern']['spec']['replicas'] == '2'

    def test_element_outside_foreach_rejected(self):
        with pytest.raises(SubstitutionError):
            vars_mod.validate_element_in_foreach(
                {'validate': {'pattern': {'a': '{{element.image}}'}}})
        # inside foreach is fine
        vars_mod.validate_element_in_foreach(
            {'validate': {'foreach': [{'pattern': {'a': '{{element.image}}'}}]}})


class TestOperators:
    def ev(self, key, operator, value):
        return ops.evaluate(None, {'key': key, 'operator': operator, 'value': value})

    def test_equals(self):
        assert self.ev('a', 'Equals', 'a')
        assert self.ev('abc', 'Equals', 'a*')  # wildcard in value
        assert not self.ev('a', 'Equals', 'b')
        assert self.ev(3, 'Equals', 3)
        assert self.ev(3, 'Equals', '3')
        assert self.ev('1Gi', 'Equals', '1024Mi')
        assert self.ev('1h', 'Equals', '60m')
        assert self.ev(True, 'Equals', True)
        assert not self.ev(True, 'Equals', 'true')
        assert self.ev({'a': 1}, 'Equals', {'a': 1})
        assert self.ev([1, 2], 'Equals', [1, 2])

    def test_not_equals(self):
        assert self.ev('a', 'NotEquals', 'b')
        assert not self.ev(3, 'NotEquals', 3)

    def test_in_anyin(self):
        assert self.ev('a', 'In', ['a', 'b'])
        assert not self.ev('c', 'In', ['a', 'b'])
        assert self.ev('nginx:1.2', 'AnyIn', ['nginx:*'])
        assert self.ev(['a', 'x'], 'AnyIn', ['x', 'y'])
        assert not self.ev(['a', 'b'], 'AnyIn', ['x', 'y'])
        assert self.ev(['a', 'b'], 'AllIn', ['a', 'b', 'c'])
        assert not self.ev(['a', 'z'], 'AllIn', ['a', 'b', 'c'])

    def test_notin_family(self):
        assert self.ev('c', 'NotIn', ['a', 'b'])
        assert self.ev(['c'], 'AnyNotIn', ['a', 'b'])
        assert not self.ev(['a'], 'AnyNotIn', ['a'])
        assert self.ev(['c', 'd'], 'AllNotIn', ['a', 'b'])
        # AllNotIn is universal (reference allin.go:192 isAllNotIn):
        # false when ANY key element matches
        assert not self.ev(['a', 'b'], 'AllNotIn', ['a'])
        assert not self.ev(['a', 'z'], 'AllNotIn', '["a","b"]')
        # JSON-string values use bidirectional wildcard membership
        assert not self.ev(['nginx:1'], 'AllNotIn', '["nginx*"]')
        assert self.ev(['redis:7'], 'AllNotIn', '["nginx*"]')
        assert self.ev(['nginx:1'], 'AnyIn', '["nginx*"]')

    def test_in_json_string_value(self):
        assert self.ev('a', 'In', '["a", "b"]')

    def test_anyin_range(self):
        assert self.ev(5, 'AnyIn', '1-10')
        assert not self.ev(50, 'AnyIn', '1-10')
        assert self.ev([5, 100], 'AnyIn', '1-10')

    def test_numeric(self):
        assert self.ev(8080, 'GreaterThan', 1024)
        assert not self.ev(80, 'GreaterThan', 1024)
        assert self.ev(10, 'GreaterThanOrEquals', 10)
        assert self.ev(1, 'LessThan', 2)
        assert self.ev('512Mi', 'LessThan', '1Gi')
        assert self.ev('2h', 'GreaterThan', '90m')
        assert self.ev('1.2.3', 'GreaterThan', '1.0.0')  # semver
        assert self.ev('8', 'LessThanOrEquals', 8)

    def test_duration_deprecated(self):
        assert self.ev(3600, 'DurationGreaterThanOrEquals', '1h')
        assert self.ev('30m', 'DurationLessThan', 3600)

    def test_condition_blocks(self):
        conds = {'any': [
            {'key': 'a', 'operator': 'Equals', 'value': 'x'},
            {'key': 'b', 'operator': 'Equals', 'value': 'b'},
        ]}
        assert ops.evaluate_conditions(None, conds)
        conds_all = {'all': [
            {'key': 'a', 'operator': 'Equals', 'value': 'a'},
            {'key': 'b', 'operator': 'Equals', 'value': 'x'},
        ]}
        assert not ops.evaluate_conditions(None, conds_all)
        # legacy list form
        assert ops.evaluate_conditions(None, [
            {'key': 'a', 'operator': 'Equals', 'value': 'a'}])
