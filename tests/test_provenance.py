"""Decision provenance & flight recorder
(kyverno_tpu/observability/provenance.py).

Pins the per-decision attribution contract: every admission decision
and rescan row yields exactly one DecisionRecord naming its serving
path; batch rider device-time shares sum to the batch's device_eval
stage time; shed reasons match the shed ledger; cache replays carry
the verdict digest and zero device share; the flight-recorder rings
are bounded; watchdog/scan-error events dump the rings to JSONL; and
output is bit-identical with provenance on vs off.  CPU-only, tier-1,
timing-free (clocks injected where time matters).
"""

import json
import threading
import urllib.request

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.config.config import Configuration
from kyverno_tpu.policycache import cache as pcache
from kyverno_tpu.policycache.cache import Cache
from kyverno_tpu.observability import device as devtel
from kyverno_tpu.observability import provenance, tracing
from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                               set_global_registry)
from kyverno_tpu.serving import shed as shed_policy
from kyverno_tpu.serving.batcher import AdmissionBatcher
from kyverno_tpu.webhooks.handlers import ResourceHandlers
from kyverno_tpu.webhooks.server import WebhookServer

ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  background: true
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""


def pod(labels, name, uid=None):
    meta = {'name': name, 'namespace': 'default', 'labels': labels}
    if uid is not None:
        meta['uid'] = uid
    return {'apiVersion': 'v1', 'kind': 'Pod', 'metadata': meta,
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


def review_bytes(resource, uid):
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {
            'uid': uid, 'operation': 'CREATE',
            'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
            'namespace': 'default',
            'name': resource['metadata']['name'],
            'object': resource,
            'userInfo': {'username': 'alice', 'groups': []},
        }}).encode()


@pytest.fixture(scope='module')
def chain():
    """One compiled serving chain for the whole module."""
    cache = Cache()
    cache.warm_up([Policy(d) for d in yaml.safe_load_all(ENFORCE_POLICY)])
    handlers = ResourceHandlers(cache, configuration=Configuration(),
                                serving_mode='batch')
    server = WebhookServer(handlers, configuration=Configuration())
    enforce = cache.get_policies(pcache.VALIDATE_ENFORCE, 'Pod',
                                 'default')
    assert handlers.wait_device_ready(enforce, timeout=600)
    yield server, handlers
    handlers.shutdown()


@pytest.fixture
def prov():
    """Provenance + device telemetry on a fresh registry; everything
    restored afterwards."""
    registry = MetricsRegistry()
    set_global_registry(registry)
    devtel.configure(registry)
    recorder = provenance.configure(registry, flight_n=4096,
                                    dump_dir=None)
    yield recorder, registry
    provenance.disable()
    devtel.disable()
    set_global_registry(None)


def drive(server, requests, n_threads=8):
    barrier = threading.Barrier(n_threads)
    chunks = [requests[i::n_threads] for i in range(n_threads)]
    results = {}

    def work(tid):
        barrier.wait()
        for uid, p in chunks[tid]:
            results[uid] = server.handle('/validate/fail',
                                         review_bytes(p, uid))
    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    return results


def mixed_requests(n, prefix='u'):
    return [(f'{prefix}{i}',
             pod({'team': 'infra'} if i % 2 else {}, f'p-{prefix}{i}'))
            for i in range(n)]


class TestAdmissionRecords:
    def test_one_record_per_decision_and_shares_sum(self, chain, prov):
        """32 concurrent batched decisions: exactly one record each;
        riders of one batch agree on occupancy and their device-time
        shares sum to that batch's device_eval time."""
        server, handlers = chain
        recorder, registry = prov
        handlers._get_batcher().reset_stats()
        requests = mixed_requests(32)
        drive(server, requests)
        records = recorder.records()
        assert len(records) == len(requests)
        # the flight recorder and the cataloged metrics agree: every
        # record observed exactly once on the per-path histogram
        series = registry.histogram_series(
            'kyverno_tpu_decision_duration_seconds')
        assert sum(count for _key, count, _total in series) == \
            len(records)
        assert {r.uid for r in records} == {uid for uid, _ in requests}
        by_batch = {}
        for r in records:
            if r.path == 'batch':
                by_batch.setdefault(r.batch_id, []).append(r)
            else:
                assert r.path.startswith('shed:'), r.path
        assert by_batch, 'no batched decisions at all'
        for batch_id, riders in by_batch.items():
            assert batch_id
            [occupancy] = {r.occupancy for r in riders}
            assert occupancy == len(riders)
            [device_eval_s] = {r.device_eval_s for r in riders}
            assert sum(r.device_share_s for r in riders) == \
                pytest.approx(device_eval_s, rel=1e-9)
            [fp] = {r.fingerprint for r in riders}
            assert fp  # the compiled set that served the batch

    def test_sync_record_and_span_attribution(self, chain, prov):
        """A sync decision records path=sync with its whole scan as
        device share, joined to the handler span (ids both ways)."""
        server, handlers = chain
        recorder, registry = prov
        mem = tracing.configure()
        try:
            prior = handlers.serving_mode
            handlers.serving_mode = 'sync'
            try:
                server.handle('/validate/fail',
                              review_bytes(pod({}, 'p-sync'), 'u-sync'))
            finally:
                handlers.serving_mode = prior
        finally:
            pass
        [rec] = [r for r in recorder.records() if r.uid == 'u-sync']
        assert rec.path == 'sync'
        assert rec.occupancy == 1
        assert rec.device_share_s == rec.device_eval_s
        assert rec.aot_cache in ('hit', 'miss', 'aot_load')
        assert rec.engine_rev
        [root] = mem.find('webhooks/validate/fail')
        assert rec.trace_id == root.trace_id
        assert root.attributes['decision_path'] == 'sync'
        tracing.disable()
        # the cataloged per-path metrics observed this decision
        assert registry.histogram_count(
            'kyverno_tpu_decision_duration_seconds', path='sync') >= 1
        assert registry.histogram_count(
            'kyverno_tpu_decision_device_share_seconds') >= 1

    def test_shed_records_match_ledger(self, chain, prov):
        """Overflow sheds: each shed decision records shed:<reason>
        once, and per-reason record counts equal the shed ledger's."""
        server, handlers = chain
        recorder, _registry = prov
        prior = handlers._batcher
        handlers._batcher = AdmissionBatcher(
            window_ms=50, queue_cap=2,
            on_success=handlers._batch_scan_ok,
            on_failure=handlers._batch_scan_failed)
        try:
            requests = mixed_requests(24, prefix='q')
            drive(server, requests, n_threads=12)
            records = recorder.records()
            assert len(records) == len(requests)
            shed_records = [r for r in records
                            if r.path.startswith('shed:')]
            assert shed_records, 'queue_cap=2 under 12 threads must shed'
            counts = handlers._batcher.sheds.counts()
            by_reason = {}
            for r in shed_records:
                reason = r.path.split(':', 1)[1]
                assert reason in shed_policy.REASONS
                by_reason[reason] = by_reason.get(reason, 0) + 1
            for reason, n in by_reason.items():
                assert counts.get(reason, 0) == n, (reason, counts)
            # shed records land in the error ring too
            assert len(recorder.errors()) == len(shed_records)
        finally:
            custom = handlers._batcher
            if custom is not None and custom is not prior:
                custom.stop(drain=True)
            handlers._batcher = prior


class TestRescanRecords:
    def _controller(self, tmp_path):
        from kyverno_tpu.dclient.client import FakeClient
        from kyverno_tpu.reports.controllers import (
            BackgroundScanController, MetadataCache)
        import os
        os.environ['KTPU_VERDICT_CACHE_DIR'] = str(tmp_path / 'vc')
        try:
            return BackgroundScanController(
                FakeClient(), [Policy(next(iter(
                    yaml.safe_load_all(ENFORCE_POLICY))))],
                cache=MetadataCache())
        finally:
            del os.environ['KTPU_VERDICT_CACHE_DIR']

    def test_rescan_rows_batch_then_replay(self, chain, prov, tmp_path):
        """Tick 1: every row records as a rider of the tick's dense
        scan (shares sum to its device_eval).  Tick 2 (no churn): every
        row replays — digest carried, zero device share."""
        recorder, _registry = prov
        ctrl = self._controller(tmp_path)
        pods = [pod({'team': 'x'}, f'rp{i}', uid=f'uid-{i}')
                for i in range(6)]
        for p in pods:
            ctrl.enqueue(p)
        ctrl.reconcile(now=1000.0)
        records = recorder.records()
        assert len(records) == len(pods)
        assert all(r.path == 'batch' and r.source == 'rescan'
                   for r in records)
        [batch_id] = {r.batch_id for r in records}
        assert batch_id.startswith('rescan')
        [occ] = {r.occupancy for r in records}
        assert occ == len(pods)
        [device_eval_s] = {r.device_eval_s for r in records}
        assert sum(r.device_share_s for r in records) == \
            pytest.approx(device_eval_s, rel=1e-9)
        recorder.reset()
        ctrl.reset_scan_state()
        for p in pods:
            ctrl.enqueue(p)
        ctrl.reconcile(now=2000.0)
        replays = recorder.records()
        assert len(replays) == len(pods)
        for r in replays:
            assert r.path == 'cache_replay' and r.source == 'rescan'
            assert r.verdict_digest
            assert r.device_share_s == 0.0 and r.device_eval_s == 0.0
            assert r.uid.startswith('uid-')
        ctrl.close()


class TestFlightRecorder:
    def test_ring_bounds_and_error_ring(self):
        clock = {'t': 100.0}
        recorder = provenance.FlightRecorder(
            4, dump_dir=None, now=lambda: clock['t'])
        for i in range(10):
            recorder.record(provenance.DecisionRecord(
                ts=float(i), path='sync', source='admission',
                uid=f'u{i}', kind='Pod', namespace='', name='',
                operation='CREATE', duration_s=0.01, queue_wait_s=0.0,
                batch_id='', occupancy=1, device_share_s=0.0,
                device_eval_s=0.0, aot_cache='', coverage_ratio=None,
                fingerprint='', engine_rev='', verdict_digest='',
                error=''))
        for i in range(3):
            recorder.record(provenance.DecisionRecord(
                ts=float(i), path='shed:deadline', source='admission',
                uid=f'e{i}', kind='Pod', namespace='', name='',
                operation='CREATE', duration_s=0.5, queue_wait_s=0.5,
                batch_id='', occupancy=0, device_share_s=0.0,
                device_eval_s=0.0, aot_cache='', coverage_ratio=None,
                fingerprint='', engine_rev='', verdict_digest='',
                error=''))
        assert len(recorder.records()) == 4          # ring-bounded
        assert len(recorder.errors()) == 3           # separate ring
        stats = recorder.stats()
        assert stats['total'] == 13                  # counters unbounded
        assert stats['by_path'] == {'sync': 10, 'shed:deadline': 3}
        assert recorder.records(limit=2)[-1].uid == 'e2'

    def test_watchdog_and_scan_error_dump(self, tmp_path):
        """The d2h stall watchdog and a scan error both dump the rings
        to JSONL; dumps are rate-limited per trigger on the injected
        clock."""
        clock = {'t': 1000.0}
        registry = MetricsRegistry()
        devtel.configure(registry, stall_threshold_s=30.0)
        recorder = provenance.configure(
            registry, flight_n=16, dump_dir=str(tmp_path),
            now=lambda: clock['t'])
        try:
            provenance.record_decision(path='sync', uid='u1',
                                       duration_s=0.01)
            # fire the watchdog synchronously (no sleeping): the event
            # sink chain ends in the flight recorder's dump
            devtel.watchdog()._fire(45.0, {'chunk_start': 0})
            [dump1] = recorder.dump_paths
            assert 'd2h_stall' in dump1
            lines = [json.loads(x) for x in open(dump1)]
            assert lines[0]['trigger'] == 'd2h_stall'
            assert any(e.get('uid') == 'u1' for e in lines[1:])
            # rate limit: a second stall inside the window is dropped
            devtel.watchdog()._fire(45.0, {'chunk_start': 1})
            assert len(recorder.dump_paths) == 1
            # scan errors are an independent trigger
            provenance.notify_scan_error(RuntimeError('boom'))
            assert len(recorder.dump_paths) == 2
            assert 'scan_error' in recorder.dump_paths[1]
            # beyond the window the stall trigger fires again
            clock['t'] += provenance.FlightRecorder.DUMP_MIN_INTERVAL_S \
                + 1
            devtel.watchdog()._fire(45.0, {'chunk_start': 2})
            assert len(recorder.dump_paths) == 3
        finally:
            provenance.disable()
            devtel.disable()

    def test_flight_n_zero_disables(self, monkeypatch):
        monkeypatch.setenv('KTPU_FLIGHT_N', '0')
        assert provenance.configure() is None
        assert not provenance.enabled()
        # emit sites are no-ops, not errors
        assert provenance.record_decision(path='sync') is None
        assert provenance.breakdown() == {}


class TestBitIdentity:
    def test_admission_output_identical_on_off(self, chain):
        """The same requests produce byte-identical responses with
        provenance recording and with KTPU_FLIGHT_N=0 — records ride
        telemetry, never the response."""
        server, handlers = chain
        requests = mixed_requests(12, prefix='bi')
        registry = MetricsRegistry()
        devtel.configure(registry)
        provenance.configure(registry, flight_n=256, dump_dir=None)
        try:
            with_prov = drive(server, requests, n_threads=4)
            assert provenance.recorder().stats()['total'] == \
                len(requests)
        finally:
            provenance.disable()
            devtel.disable()
        without = drive(server, requests, n_threads=4)
        assert with_prov == without

    def test_rescan_reports_identical_on_off(self, tmp_path):
        from kyverno_tpu.dclient.client import FakeClient
        from kyverno_tpu.reports.controllers import (
            BackgroundScanController, MetadataCache)
        policy = Policy(next(iter(yaml.safe_load_all(ENFORCE_POLICY))))
        pods = [pod({'team': 'x'} if i % 2 else {}, f'bp{i}',
                    uid=f'buid-{i}') for i in range(4)]

        def run_tick(enabled, sub):
            import os
            os.environ['KTPU_VERDICT_CACHE_DIR'] = \
                str(tmp_path / sub)
            try:
                ctrl = BackgroundScanController(FakeClient(), [policy],
                                                cache=MetadataCache())
            finally:
                del os.environ['KTPU_VERDICT_CACHE_DIR']
            if enabled:
                provenance.configure(MetricsRegistry(), flight_n=64,
                                     dump_dir=None)
            try:
                for p in pods:
                    ctrl.enqueue(p)
                return ctrl.reconcile(now=1234.0)
            finally:
                if enabled:
                    provenance.disable()
                ctrl.close()
        on = run_tick(True, 'on')
        off = run_tick(False, 'off')
        assert json.dumps(on, sort_keys=True, default=str) == \
            json.dumps(off, sort_keys=True, default=str)


class TestDebugEndpoint:
    def test_debug_decisions_and_trace_filters(self, prov):
        from kyverno_tpu.observability.profiling import ProfilingServer
        recorder, _registry = prov
        provenance.record_decision(path='sync', uid='d1',
                                   duration_s=0.01)
        provenance.record_decision(path='shed:deadline', uid='d2',
                                   duration_s=0.5)
        provenance.record_decision(path='cache_replay', uid='d3',
                                   verdict_digest='abc123')
        mem = tracing.configure()
        with tracing.start_span('kyverno/rescan'):
            pass
        with tracing.start_span('kyverno/rescan'):
            pass
        srv = ProfilingServer(port=0)
        port = srv.start()
        try:
            base = f'http://127.0.0.1:{port}'
            body = json.loads(urllib.request.urlopen(
                f'{base}/debug/decisions').read())
            assert body['enabled'] is True
            assert body['stats']['total'] == 3
            assert [d['uid'] for d in body['decisions']] == \
                ['d1', 'd2', 'd3']
            assert [d['uid'] for d in body['errors']] == ['d2']
            assert body['decisions'][2]['verdict_digest'] == 'abc123'
            limited = json.loads(urllib.request.urlopen(
                f'{base}/debug/decisions?limit=1').read())
            assert [d['uid'] for d in limited['decisions']] == ['d3']
            # /debug/traces filters (flight-recorder follow-ups)
            spans = mem.find('kyverno/rescan')
            tid = spans[0].trace_id
            traces = json.loads(urllib.request.urlopen(
                f'{base}/debug/traces?trace_id={tid}').read())
            assert {s['traceId'] for s in traces['spans']} == {tid}
            one = json.loads(urllib.request.urlopen(
                f'{base}/debug/traces?limit=1').read())
            assert len(one['spans']) == 1
        finally:
            srv.stop()
            tracing.disable()
