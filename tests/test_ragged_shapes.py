"""Ragged canonical batch shapes (ISSUE 9).

Mask-boundary correctness: row counts {1, capacity-1, capacity,
capacity+1 (spill)} must be bit-identical to the dense host oracle
across validate AND mutate; padding rows must be invisible to every
cross-row consumer (compact fail-detail selection, mesh verdict
summary, mutate edit bitmasks).  Plus: the canonical capacity table
itself, AOT load-rejection accounting, and the second-process probe
asserting a fresh scan across row counts loads ≤ 2 executables per
policy set.  CPU-only, tier-1.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.compiler.shapes import (canonical_capacity, canonical_caps,
                                         small_capacity)
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                               set_global_registry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def policy(name, rule):
    return Policy({'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
                   'metadata': {'name': name, 'annotations': {
                       'pod-policies.kyverno.io/autogen-controllers':
                           'none'}},
                   'spec': {'rules': [rule]}})


def validate_pack():
    return [
        policy('require-app', {
            'name': 'check-app',
            'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
            'validate': {'message': 'app label required',
                         'pattern': {'metadata': {
                             'labels': {'app': '?*'}}}}}),
        policy('limit-replicas', {
            'name': 'max-containers',
            'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
            'validate': {
                'message': 'too many containers',
                'deny': {'conditions': {'any': [
                    {'key': '{{ length(request.object.spec.containers) }}',
                     'operator': 'GreaterThan', 'value': 3}]}}}}),
    ]


def pod(i):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{i}', 'namespace': 'default',
                         'labels': {'app': f'a{i}'} if i % 3 else {}},
            'spec': {'containers': [
                {'name': f'c{k}', 'image': 'nginx:1'}
                for k in range(1 + i % 4)]}}


# ---------------------------------------------------------------------------
# the canonical capacity table


class TestShapeTable:
    def test_default_table_is_small_and_chunk(self):
        caps = canonical_caps(chunk=16384, small=64)
        assert caps == (64, 16384)
        assert canonical_capacity(1, chunk=16384, small=64) == 64
        assert canonical_capacity(64, chunk=16384, small=64) == 64
        assert canonical_capacity(65, chunk=16384, small=64) == 16384
        # spill: the top entry also serves row counts beyond it
        # (callers chunk above it)
        assert canonical_capacity(99999, chunk=16384, small=64) == 16384

    def test_env_override_is_the_whole_table(self, monkeypatch):
        monkeypatch.setenv('KTPU_CANONICAL_CAPS', '32, 512,4096')
        assert canonical_caps() == (32, 512, 4096)
        assert canonical_capacity(33) == 512
        monkeypatch.setenv('KTPU_CANONICAL_CAPS', 'bogus')
        assert canonical_caps(chunk=128, small=8) == (8, 128)

    def test_small_capacity(self):
        assert small_capacity(small=16) == 16

    def test_batcher_default_max_is_small_capacity(self, monkeypatch):
        monkeypatch.delenv('KTPU_BATCH_MAX', raising=False)
        from kyverno_tpu.serving.batcher import AdmissionBatcher
        b = AdmissionBatcher(window_ms=1, queue_cap=4)
        try:
            assert b.max_batch == small_capacity()
        finally:
            b.stop(drain=False, timeout=5)


# ---------------------------------------------------------------------------
# encoder row-validity lane


class TestRowValidLane:
    def test_rowvalid_marks_capacity_padding(self):
        from kyverno_tpu.compiler.encode import encode_batch
        scanner = BatchScanner(validate_pack())
        cap = canonical_capacity(3, chunk=scanner.CHUNK,
                                 small=scanner.SMALL_BATCH)
        batch = encode_batch([pod(i) for i in range(3)], scanner.cps,
                             padded_n=cap)
        t = batch.tensors()
        rv = t['__rowvalid__']
        assert rv.shape == (cap,)
        assert rv[:3].all() and not rv[3:].any()

    def test_mutate_valid_lane_and_kernel_mask(self):
        from kyverno_tpu.mutate import MutateScanner
        from kyverno_tpu.mutate.encode import encode_mutate_batch
        from kyverno_tpu.mutate.kernel import MUT_SKIP, MutateKernel
        pol = policy('add-label', {
            'name': 'r',
            'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
            'mutate': {'patchStrategicMerge': {
                'metadata': {'labels': {'team': 'x'}}}}})
        scanner = MutateScanner([pol])
        assert scanner.ok
        cap = canonical_capacity(2)
        lanes = encode_mutate_batch([pod(0), pod(1)], scanner.program,
                                    padded_n=cap)
        assert lanes['valid'][:2].all() and not lanes['valid'][2:].any()
        status, edits, reason = MutateKernel(scanner.program)(lanes)
        # live rows edit (label absent); padding rows — which encode as
        # all-MISSING and would otherwise read "every edit applies" —
        # are masked to SKIP / empty bitmask / no reason in-kernel
        assert (status[:2] != MUT_SKIP).any()
        assert (status[2:] == MUT_SKIP).all()
        assert (edits[2:] == 0).all()
        assert (reason[2:] == 0).all()


# ---------------------------------------------------------------------------
# mask-boundary bit-identity: validate


class TestValidateMaskBoundaries:
    def _host(self, policies, resource):
        engine = Engine()
        host = {}
        for pol in policies:
            resp = engine.apply_background_checks(
                PolicyContext(pol, new_resource=resource))
            if resp.policy_response.rules:
                host[pol.name] = {r.name: (r.status, r.message)
                                  for r in resp.policy_response.rules}
        return host

    def test_boundary_row_counts_match_dense_host_oracle(self):
        policies = validate_pack()
        scanner = BatchScanner(policies)
        # shrink the chunk so the spill (capacity+1) case streams two
        # canonically-shaped parts instead of a 16384-row pad
        scanner.CHUNK = 128
        cap = scanner.SMALL_BATCH  # the small canonical capacity
        for n in (1, cap - 1, cap, cap + 1, 129):
            resources = [pod(i) for i in range(n)]
            rows = scanner.scan([json.loads(json.dumps(r))
                                 for r in resources])
            assert len(rows) == n
            for resource, responses in zip(resources, rows):
                got = {resp.policy_response.policy_name:
                       {r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
                       for resp in responses
                       if resp.policy_response.rules}
                assert got == self._host(policies, resource), \
                    f'divergence at n={n} on {resource["metadata"]["name"]}'

    def test_boundary_counts_compile_canonical_shapes_only(self):
        from kyverno_tpu.observability import device as devtel
        reg = devtel.configure(MetricsRegistry())
        try:
            scanner = BatchScanner(validate_pack())
            scanner.CHUNK = 128
            for n in (1, 63, 64, 65, 128, 129):
                scanner.scan_statuses([pod(i) for i in range(n)])
            c = 'kyverno_tpu_compile_cache_requests_total'
            compiled = reg.counter_value(c, result='miss') + \
                reg.counter_value(c, result='aot_load')
            assert compiled <= 2, \
                f'{compiled} executables for one policy set'
        finally:
            devtel.configure(None)

    def test_warmup_shapes_covers_the_table(self):
        scanner = BatchScanner(validate_pack())
        scanner.CHUNK = 128
        timings = scanner.warmup_shapes()
        assert sorted(timings) == [64, 128]
        assert all(v >= 0 for v in timings.values())
        # warmed executables serve a real scan without a fresh compile
        from kyverno_tpu.observability import device as devtel
        reg = devtel.configure(MetricsRegistry())
        try:
            scanner.scan_statuses([pod(i) for i in range(65)])
            c = 'kyverno_tpu_compile_cache_requests_total'
            assert reg.counter_value(c, result='miss') == 0
            assert reg.counter_value(c, result='hit') >= 1
        finally:
            devtel.configure(None)


# ---------------------------------------------------------------------------
# mask-boundary bit-identity: mutate


class TestMutateMaskBoundaries:
    def _pack(self):
        return [
            policy('add-team', {
                'name': 'team',
                'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                'mutate': {'patchStrategicMerge': {
                    'metadata': {'labels': {'+(team)': 'core'}}}}}),
            policy('dns-policy', {
                'name': 'dns',
                'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                'mutate': {'patchStrategicMerge': {
                    'spec': {'dnsPolicy': 'ClusterFirst'}}}}),
        ]

    @staticmethod
    def _host_chain(policies, doc):
        engine = Engine()
        pctx = PolicyContext(None,
                             new_resource=json.loads(json.dumps(doc)))
        steps = []
        for pol in policies:
            ctx = pctx.copy()
            ctx.policy = pol
            er = engine.mutate(ctx)
            steps.append((pol.name,
                          [(r.name, str(r.status), r.message, r.patches)
                           for r in er.policy_response.rules]))
            if not er.is_successful():
                break
            pctx = pctx.copy()
            pctx.new_resource = er.patched_resource or pctx.new_resource
            pctx.json_context.add_resource(pctx.new_resource)
        return steps, pctx.new_resource

    def test_boundary_row_counts_match_host_chain(self, monkeypatch):
        # a small canonical table keeps the spill case fast
        monkeypatch.setenv('KTPU_CANONICAL_CAPS', '16,64')
        from kyverno_tpu.mutate import MutateScanner
        policies = self._pack()
        scanner = MutateScanner(policies)
        assert scanner.ok
        for n in (1, 15, 16, 17):
            docs = [pod(i) for i in range(n)]
            rows = scanner.scan([json.loads(json.dumps(d)) for d in docs])
            assert len(rows) == n
            for doc, (steps, patched) in zip(docs, rows):
                h_steps, h_patched = self._host_chain(policies, doc)
                assert patched == h_patched, f'n={n}'
                got = [(pol.name,
                        [(r.name, str(r.status), r.message, r.patches)
                         for r in er.policy_response.rules])
                       for pol, er in steps]
                assert got == h_steps, f'n={n}'


# ---------------------------------------------------------------------------
# mesh verdict summary ignores padding rows


class TestMeshRowMask:
    def test_summary_counts_only_live_rows(self):
        import jax
        from kyverno_tpu.parallel.mesh import (distributed_scan_step,
                                               make_mesh)
        policies = validate_pack()
        scanner = BatchScanner(policies)
        mesh = make_mesh(jax.devices()[:1])
        resources = [pod(i) for i in range(5)]
        statuses, summary = distributed_scan_step(
            scanner.cps, mesh, resources)
        assert statuses.shape[0] == 5
        # the canonical capacity padded well past 5 rows; the summary
        # histogram must still total live rows × programs exactly
        assert int(summary.sum()) == 5 * len(scanner.cps.programs)


# ---------------------------------------------------------------------------
# AOT load rejection accounting


class TestAotLoadRejection:
    @pytest.fixture(autouse=True)
    def _store(self, tmp_path, monkeypatch):
        from kyverno_tpu.aotcache.store import reset_default_store
        monkeypatch.setenv('KTPU_AOT_CACHE_DIR', str(tmp_path / 'aot'))
        reset_default_store()
        self.registry = MetricsRegistry()
        set_global_registry(self.registry)
        yield
        set_global_registry(None)
        reset_default_store()

    def _reason_count(self, reason):
        return self.registry.counter_value(
            'kyverno_tpu_aot_load_rejected_total', reason=reason)

    def test_feature_mismatch_rejected_and_dropped(self):
        from kyverno_tpu.compiler import aot
        store = aot.default_store()
        key = 'f' * 32
        meta = aot._compile_meta()
        meta['host_features'] = 'not-this-machine'
        store.put(key, aot._pack_blob(b'payload', None, None, meta))
        assert aot.load_executable(key) is None
        assert self._reason_count('feature_mismatch') == 1
        assert store.load(key) is None  # dropped, not retried

    def test_env_scope_mismatch_rejected(self):
        from kyverno_tpu.compiler import aot
        store = aot.default_store()
        key = 'e' * 32
        meta = aot._compile_meta()
        meta['env_scope'] = 'compiled-with-tpu-plugin'
        store.put(key, aot._pack_blob(b'payload', None, None, meta))
        assert aot.load_executable(key) is None
        assert self._reason_count('env_mismatch') == 1

    def test_undecodable_blob_rejected(self):
        from kyverno_tpu.compiler import aot
        store = aot.default_store()
        key = 'u' * 32
        store.put(key, b'Xnot-a-codec')
        assert aot.load_executable(key) is None
        assert self._reason_count('undecodable') == 1

    def test_matching_meta_reaches_deserialize(self):
        # a well-framed entry with THIS process's meta proceeds to XLA
        # deserialization; garbage payload then fails there and is
        # rejected with deserialize_failed (never raised)
        from kyverno_tpu.compiler import aot
        store = aot.default_store()
        key = 'd' * 32
        store.put(key, aot._pack_blob(b'garbage', None, None,
                                      aot._compile_meta()))
        assert aot.load_executable(key) is None
        assert self._reason_count('deserialize_failed') == 1

    def test_legacy_three_tuple_frame_is_undecodable(self):
        import pickle
        import zlib
        from kyverno_tpu.compiler import aot
        store = aot.default_store()
        key = 'l' * 32
        raw = pickle.dumps((b'payload', None, None))
        store.put(key, b'D' + zlib.compress(raw, 3))
        assert aot.load_executable(key) is None
        assert self._reason_count('undecodable') == 1


# ---------------------------------------------------------------------------
# acceptance: second process loads ≤ 2 executables across row counts


_PROBE_SCRIPT = r'''
import json, sys
from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import device as devtel
from kyverno_tpu.observability.metrics import MetricsRegistry

POLICY = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'require-labels', 'annotations': {
        'pod-policies.kyverno.io/autogen-controllers': 'none'}},
    'spec': {'validationFailureAction': 'Enforce', 'rules': [
        {'name': 'check-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'app label required',
                      'pattern': {'metadata': {'labels': {'app': '?*'}}}}},
    ]}}


def pod(i):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{i}', 'namespace': 'default',
                         'labels': {'app': 'x'} if i % 2 else {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}


reg = devtel.configure(MetricsRegistry())
from kyverno_tpu.compiler.scan import BatchScanner
scanner = BatchScanner([Policy(POLICY)])
out = {}
# the acceptance sweep: row counts from 1 through past the chunk —
# every size must reuse one of the ≤2 canonical executables
for n in (1, 63, 64, 65, 256, 300):
    status, detail, match = scanner.scan_statuses(
        [pod(i) for i in range(n)])
    out[str(n)] = status.tolist()
from kyverno_tpu.compiler import aot
aot.flush_stores()
C = 'kyverno_tpu_compile_cache_requests_total'
print(json.dumps({
    'miss': reg.counter_value(C, result='miss'),
    'aot_load': reg.counter_value(C, result='aot_load'),
    'rows': out,
}))
'''


def _run_probe(cache_dir, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'PYTHONPATH': REPO,
        'KTPU_SCAN_CHUNK': '256',
        'KTPU_SMALL_BATCH': '64',
        'KTPU_ENCODE_PROCS': '0',
        'KTPU_AOT': '1',
        'KTPU_AOT_CACHE_DIR': os.path.join(str(cache_dir), 'aot'),
        'KTPU_COMPILE_CACHE': os.path.join(str(cache_dir), 'xla'),
    })
    out = subprocess.run([sys.executable, '-c', _PROBE_SCRIPT],
                         env=env, cwd=REPO, capture_output=True,
                         text=True, timeout=timeout)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_second_process_loads_at_most_two_executables(tmp_path):
    """ISSUE 9 acceptance: scanning every boundary row count from 1 to
    past the chunk, a fresh process against a warm store performs zero
    fresh compiles and loads ≤ 2 executables for the policy set — the
    power-of-two bucket zoo (one per size class) is gone — with
    bit-identical status matrices."""
    first = _run_probe(tmp_path)
    assert first['miss'] <= 2, first
    second = _run_probe(tmp_path)
    assert second['miss'] == 0, second
    assert 1 <= second['aot_load'] <= 2, second
    assert second['rows'] == first['rows']
