from kyverno_tpu.utils import wildcard
from kyverno_tpu.utils.duration import parse_duration, is_duration, format_duration
from kyverno_tpu.utils.quantity import Quantity, is_quantity


class TestWildcard:
    def test_star(self):
        assert wildcard.match('*', 's3:GetObject')
        assert wildcard.match('s3:*', 's3:ListParts')
        assert wildcard.match('my-bucket/In*', 'my-bucket/India/Karnataka/')
        assert not wildcard.match('my-bucket/In*', 'my-bucket/Karnataka/India/')

    def test_empty(self):
        assert wildcard.match('', '')
        assert not wildcard.match('', 'x')

    def test_exact(self):
        assert wildcard.match('s3:ListBucket', 's3:ListBucket')
        assert not wildcard.match('s3:ListBucketMultipartUploads', 's3:ListBucket')

    def test_question(self):
        assert wildcard.match('a?c', 'abc')
        assert not wildcard.match('a?c', 'ac')
        assert wildcard.match('*.??m', 'x.com')

    def test_multi_star(self):
        assert wildcard.match('a*b*c', 'axxbyyc')
        assert wildcard.match('*a*', 'za')
        assert not wildcard.match('a*b*c', 'axxbyy')


class TestQuantity:
    def test_plain(self):
        assert Quantity.parse('10').cmp(Quantity.parse('10')) == 0
        assert Quantity.parse('9').cmp(Quantity.parse('10')) == -1

    def test_binary_si(self):
        assert Quantity.parse('1Ki').cmp(Quantity.parse('1024')) == 0
        assert Quantity.parse('1Gi').cmp(Quantity.parse('1024Mi')) == 0

    def test_decimal_si(self):
        assert Quantity.parse('1500m').cmp(Quantity.parse('1.5')) == 0
        assert Quantity.parse('1k').cmp(Quantity.parse('1000')) == 0
        assert Quantity.parse('100m').cmp(Quantity.parse('0.1')) == 0

    def test_exponent(self):
        assert Quantity.parse('1e3').cmp(Quantity.parse('1000')) == 0
        assert Quantity.parse('1.5E2').cmp(Quantity.parse('150')) == 0

    def test_mixed_compare(self):
        assert Quantity.parse('1Gi').cmp(Quantity.parse('1G')) == 1  # 2^30 > 10^9

    def test_negative(self):
        assert Quantity.parse('-1').cmp(Quantity.parse('1')) == -1

    def test_invalid(self):
        assert not is_quantity('abc')
        assert not is_quantity('1XX')
        assert is_quantity('10Mi')


class TestDuration:
    def test_basic(self):
        assert parse_duration('1s') == 10**9
        assert parse_duration('300ms') == 300 * 10**6
        assert parse_duration('2h45m') == (2 * 3600 + 45 * 60) * 10**9
        assert parse_duration('1.5h') == int(1.5 * 3600) * 10**9

    def test_zero_and_sign(self):
        assert parse_duration('0') == 0
        assert parse_duration('-1m') == -60 * 10**9
        assert parse_duration('+2s') == 2 * 10**9

    def test_invalid(self):
        assert not is_duration('10')   # missing unit
        assert not is_duration('abc')
        assert not is_duration('')
        assert is_duration('10ns')

    def test_format(self):
        assert format_duration(0) == '0s'
        assert format_duration(10**9) == '1s'
        assert format_duration(90 * 10**9) == '1m30s'
        assert format_duration(3661 * 10**9) == '1h1m1s'
        assert format_duration(3600 * 10**9) == '1h0m0s'  # Go prints zero m/s
