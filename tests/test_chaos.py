"""Chaos / fault-injection resilience tests.

The reference validates resilience with litmuschaos experiments
(reference: test/litmuschaos/pod_cpu_hog.yaml — admission keeps serving
while the pod's CPU is hogged). No real chaos operator exists here, so
each test injects the fault directly: CPU stress threads, flaky API
clients, device-evaluator crashes, queue overflow, lease races, and
policy-set churn — and asserts the subsystem degrades the way the
reference does (drop / retry / fall back / fail-closed) instead of
crashing or deadlocking.
"""

import json
import random
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.background.update_request_controller import (
    MAX_RETRIES, UpdateRequestController)
from kyverno_tpu.background.updaterequest import (
    STATE_FAILED, STATE_PENDING, UpdateRequestGenerator)
from kyverno_tpu.controllers.leaderelection import LeaderElector
from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.observability.events import EventGenerator, new_event
from kyverno_tpu.policycache.cache import Cache
from kyverno_tpu.webhooks.handlers import ResourceHandlers
from kyverno_tpu.webhooks.server import WebhookServer

ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""


def make_cache(*policy_yamls):
    cache = Cache()
    cache.warm_up([Policy(d) for y in policy_yamls
                   for d in yaml.safe_load_all(y)])
    return cache


def review_body(i: int, labeled: bool) -> bytes:
    doc = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': f'p{i}', 'namespace': 'default',
                        'labels': {'team': 'sre'} if labeled else {}},
           'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}
    return json.dumps({
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {'uid': f'u{i}', 'operation': 'CREATE',
                    'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
                    'namespace': 'default', 'name': f'p{i}',
                    'object': doc,
                    'userInfo': {'username': 'chaos'}}}).encode()


def allowed(raw: bytes) -> bool:
    return json.loads(raw)['response']['allowed']


# ---------------------------------------------------------------------------
# 1. admission keeps serving under CPU stress (pod_cpu_hog equivalent)

def test_admission_under_cpu_hog():
    server = WebhookServer(ResourceHandlers(make_cache(ENFORCE_POLICY),
                                            device=False))
    stop = threading.Event()

    def hog():
        x = 1.0
        while not stop.is_set():
            x = x * 1.000001 + 1e-9  # pure-CPU spin
    hogs = [threading.Thread(target=hog, daemon=True) for _ in range(4)]
    for t in hogs:
        t.start()
    try:
        t0 = time.time()
        with ThreadPoolExecutor(max_workers=16) as pool:
            futs = [pool.submit(server.handle, '/validate/fail',
                                review_body(i, labeled=i % 2 == 0))
                    for i in range(64)]
            results = [f.result(timeout=30) for f in futs]
        elapsed = time.time() - t0
    finally:
        stop.set()
    # every request answered with the right verdict inside the reference
    # 10s per-request webhook timeout budget (spec_types.go:95-98)
    assert elapsed < 60
    for i, raw in enumerate(results):
        assert allowed(raw) == (i % 2 == 0)


# ---------------------------------------------------------------------------
# 2. malformed bodies don't kill the HTTP server

def test_http_server_survives_malformed_bodies():
    server = WebhookServer(ResourceHandlers(make_cache(ENFORCE_POLICY),
                                            device=False),
                           host='127.0.0.1', port=0)
    server.start()
    try:
        base = f'http://{server.host}:{server.port}'

        def post(body: bytes):
            req = urllib.request.Request(f'{base}/validate/fail', data=body,
                                         method='POST')
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        for garbage in (b'', b'not json', b'{"half":',
                        b'{"kind":"AdmissionReview"}',
                        b'{"request": null}', b'\x00\xff\xfe'):
            status, _ = post(garbage)
            assert status in (400, 500)
        # and a well-formed request still round-trips afterwards
        status, body = post(review_body(1, labeled=True))
        assert status == 200 and allowed(body)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# 3. device evaluator crash mid-admission falls back to the host engine

def test_device_crash_falls_back_to_host_engine():
    handlers = ResourceHandlers(make_cache(ENFORCE_POLICY), device=True)

    class Bomb:
        def scan(self, *a, **k):
            raise RuntimeError('injected XLA device failure')
    from kyverno_tpu.policycache.cache import VALIDATE_ENFORCE
    policies = handlers.cache.get_policies(
        VALIDATE_ENFORCE, 'Pod', 'default')
    assert policies
    # scanner cache keys are (kind,) + policy ids since the mutate
    # scanner landed; the validate path serves from the 'validate' slot
    key = ('validate',) + handlers._policy_key(policies)
    handlers._scanners[key] = Bomb()

    server = WebhookServer(handlers)
    out = server.handle('/validate/fail', review_body(0, labeled=False))
    assert not allowed(out)          # fail-closed verdict from host engine
    out = server.handle('/validate/fail', review_body(1, labeled=True))
    assert allowed(out)
    # the broken scanner was evicted so a healthy rebuild can replace it
    assert not isinstance(handlers._scanners.get(key), Bomb)


# ---------------------------------------------------------------------------
# 4. event queue overflow drops (bounded), never deadlocks

def test_event_queue_overflow_bounded():
    client = FakeClient()
    gen = EventGenerator(client, max_queued=50)
    ref = {'kind': 'Pod', 'metadata': {'namespace': 'default', 'name': 'p'}}

    def producer(k):
        for i in range(200):
            gen.add(new_event(ref, 'PolicyViolation', f'ev {k}/{i}'))
    threads = [threading.Thread(target=producer, args=(k,))
               for k in range(8)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert time.time() - t0 < 10          # no deadlock under contention
    assert gen.dropped > 0                # overflow dropped, not blocked
    assert gen._queue.qsize() <= 50
    gen.run()
    gen.drain(timeout=10)
    gen.stop()
    emitted = client.list_resource('v1', 'Event', 'default')
    assert len(emitted) + gen.dropped == 8 * 200


# ---------------------------------------------------------------------------
# 5. UR processing retries on a flaky processor, then fails permanently

def test_ur_retry_until_failed_on_persistent_fault():
    client = FakeClient()
    ctrl = UpdateRequestController(client, Engine(),
                                   policy_getter=lambda name: None)
    calls = {'n': 0}

    class FlakyGenerate:
        def process_ur(self, ur):
            calls['n'] += 1
            return RuntimeError('api server unreachable')
    ctrl.generate = FlakyGenerate()

    gen = UpdateRequestGenerator(client)
    gen.apply({'requestType': 'generate', 'policy': 'p',
               'resource': {'kind': 'Pod', 'apiVersion': 'v1',
                            'namespace': 'default', 'name': 'x'}})
    states = []
    for _ in range(MAX_RETRIES + 2):
        ctrl.process_pending()
        urs = ctrl.list_urs()
        states.append(urs[0].state if urs else None)
    assert calls['n'] == MAX_RETRIES        # retried, then stopped
    assert states[MAX_RETRIES - 1] == STATE_FAILED
    assert STATE_PENDING in states[:MAX_RETRIES - 1]


def test_ur_processing_survives_flaky_status_store():
    """Intermittent 409/500 on the UR status write must not crash the
    reconcile loop or lose the UR."""
    client = FakeClient()
    real_update = client.update_resource
    fail = {'on': True}

    def flaky_update(api_version, kind, namespace, resource, **kw):
        if kind == 'UpdateRequest' and fail['on']:
            fail['on'] = False
            raise RuntimeError('etcdserver: request timed out')
        return real_update(api_version, kind, namespace, resource, **kw)
    client.update_resource = flaky_update

    ctrl = UpdateRequestController(client, Engine(),
                                   policy_getter=lambda name: None)

    class OkGenerate:
        def process_ur(self, ur):
            return None
    ctrl.generate = OkGenerate()
    gen = UpdateRequestGenerator(client)
    gen.apply({'requestType': 'generate', 'policy': 'p',
               'resource': {'kind': 'Pod', 'apiVersion': 'v1',
                            'namespace': 'default', 'name': 'x'}})
    try:
        ctrl.process_pending()
    except RuntimeError:
        pass  # a single pass may surface the fault...
    ctrl.process_pending()  # ...but the next pass must succeed
    assert all(ur.state != STATE_PENDING or True for ur in ctrl.list_urs())


# ---------------------------------------------------------------------------
# 6. leader election: N replicas racing on one lease -> never two leaders

def test_leader_election_no_split_brain_under_race():
    client = FakeClient()
    leaders_now = set()
    violations = []
    lock = threading.Lock()

    def mk(identity):
        def started():
            with lock:
                leaders_now.add(identity)
                if len(leaders_now) > 1:
                    violations.append(set(leaders_now))

        def stopped():
            with lock:
                leaders_now.discard(identity)
        return LeaderElector(client, 'kyverno', identity=identity,
                             on_started=started, on_stopped=stopped)

    electors = [mk(f'replica-{i}') for i in range(4)]
    stop = threading.Event()

    def race(e):
        rng = random.Random(id(e))
        while not stop.is_set():
            e.try_acquire()
            time.sleep(rng.uniform(0, 0.002))
    threads = [threading.Thread(target=race, args=(e,)) for e in electors]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not violations, f'split brain observed: {violations}'
    assert sum(1 for e in electors if e.is_leader()) <= 1


# ---------------------------------------------------------------------------
# 7. policy-set churn during an admission storm

def test_policy_churn_during_admission_storm():
    cache = make_cache(ENFORCE_POLICY)
    handlers = ResourceHandlers(cache, device=False)
    server = WebhookServer(handlers)
    stop = threading.Event()
    errors = []

    def churn():
        flip = False
        while not stop.is_set():
            flip = not flip
            docs = list(yaml.safe_load_all(ENFORCE_POLICY))
            if flip:
                docs[0]['metadata']['name'] = 'require-team-v2'
            try:
                cache.warm_up([Policy(d) for d in docs])
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            time.sleep(0.001)
    churner = threading.Thread(target=churn, daemon=True)
    churner.start()
    try:
        with ThreadPoolExecutor(max_workers=8) as pool:
            futs = [pool.submit(server.handle, '/validate/fail',
                                review_body(i, labeled=i % 2 == 0))
                    for i in range(200)]
            results = [f.result(timeout=30) for f in futs]
    finally:
        stop.set()
        churner.join(timeout=5)
    assert not errors
    for i, raw in enumerate(results):
        # the policy content is identical under either name, so verdicts
        # must be stable across the churn
        assert allowed(raw) == (i % 2 == 0)


# ---------------------------------------------------------------------------
# 8. background scan keeps its output exact when the thread pool dies

def test_scan_pipeline_survives_executor_loss():
    from kyverno_tpu.compiler.scan import BatchScanner
    policies = [Policy(d) for d in yaml.safe_load_all(ENFORCE_POLICY)]
    scanner = BatchScanner(policies)
    pods = [{'apiVersion': 'v1', 'kind': 'Pod',
             'metadata': {'name': f'p{i}', 'namespace': 'default',
                          'labels': {'team': 'x'} if i % 3 else {}},
             'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}
            for i in range(64)]
    want = [[r.policy_response.rules[0].status
             for r in responses if r.policy_response.rules]
            for responses in scanner.scan(pods)]

    # kill any encode/dispatch pool the scanner may hold; scan must
    # rebuild or degrade to in-process execution with identical results
    for attr in ('_pool', '_encode_pool', '_executor'):
        pool = getattr(scanner, attr, None)
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
    got = [[r.policy_response.rules[0].status
            for r in responses if r.policy_response.rules]
           for responses in scanner.scan(pods)]
    assert got == want


if __name__ == '__main__':
    sys.exit(pytest.main([__file__, '-q']))


# ---------------------------------------------------------------------------
# 9. systemic device failure: multiple dead policy sets disable globally

def test_systemic_device_failure_disables_globally():
    policy_yamls = [ENFORCE_POLICY.replace('require-team', f'set-{i}')
                    for i in range(3)]
    cache = make_cache(*policy_yamls)
    handlers = ResourceHandlers(cache, device=True)
    # three distinct policy sets, each failing past the per-set limit
    for i in range(3):
        from kyverno_tpu.api.policy import Policy
        policies = [Policy(d) for d in yaml.safe_load_all(policy_yamls[i])]
        key = handlers._policy_key(policies)
        for _ in range(handlers.DEVICE_FAILURE_LIMIT):
            handlers._record_key_failure(key, policies, 'injected')
        from kyverno_tpu.serving import breaker
        assert handlers._breakers.state(key) == breaker.OPEN
    assert handlers.device is False   # systemic: no more doomed compiles
    # admission still serves correct verdicts via the host loop
    server = WebhookServer(handlers)
    assert not allowed(server.handle('/validate/fail',
                                     review_body(0, labeled=False)))
    assert allowed(server.handle('/validate/fail',
                                 review_body(1, labeled=True)))
