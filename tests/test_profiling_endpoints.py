"""ProfilingServer debug surface (ISSUE 14 satellites 1 + 4).

The endpoints existed for five PRs with zero coverage.  Pins: the
self-registering route table (one source for dispatch, the ``/debug/``
index, the 404-with-index response, and the README table — drift
checked here), the pprof analogues (thread stacks, bounded sampling
profile), ``/metrics`` content-type, the new ledger/SLO/deep-profile
routes, and concurrent GETs through the threading server.  CPU-only,
tier-1.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from kyverno_tpu.observability import executables, profiling, slo
from kyverno_tpu.observability.metrics import MetricsRegistry
from kyverno_tpu.observability.profiling import (PROFILE_KEEP,
                                                 ProfilingServer,
                                                 deep_profile,
                                                 render_debug_index,
                                                 render_debug_table,
                                                 routes, sample_profile,
                                                 thread_stacks)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_modules():
    yield
    executables.disable()
    slo.disable()


@pytest.fixture()
def server():
    srv = ProfilingServer(port=0)
    srv.start()
    yield srv
    srv.stop()


def get(srv, path):
    """(status, content_type, body) for a GET against the server —
    HTTPError carries the 4xx/5xx responses."""
    url = f'http://127.0.0.1:{srv.port}{path}'
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.headers.get('Content-Type'), \
                resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get('Content-Type'), e.read().decode()


class TestPprofSurface:
    def test_thread_stacks_names_live_threads(self):
        done = threading.Event()
        t = threading.Thread(target=done.wait,
                             name='ktpu-test-sleeper', daemon=True)
        t.start()
        try:
            stacks = thread_stacks()
            assert 'ktpu-test-sleeper' in stacks
            assert 'thread ' in stacks
        finally:
            done.set()
            t.join()

    def test_sample_profile_is_time_bounded(self):
        done = threading.Event()
        t = threading.Thread(target=done.wait, name='busy', daemon=True)
        t.start()
        try:
            t0 = time.monotonic()
            out = sample_profile(0.1, hz=200)
            elapsed = time.monotonic() - t0
            assert elapsed < 2.0  # bounded: ~0.1s of sampling
            assert out.strip()  # folded stacks (or the idle marker)
        finally:
            done.set()
            t.join()

    def test_goroutine_endpoint(self, server):
        code, ctype, body = get(server, '/debug/pprof/goroutine')
        assert code == 200 and 'thread ' in body

    def test_profile_endpoint_rejects_bad_seconds(self, server):
        code, _, body = get(server, '/debug/pprof/profile?seconds=zap')
        assert code == 400 and 'seconds' in body

    def test_profile_endpoint_samples(self, server):
        code, ctype, body = get(server,
                                '/debug/pprof/profile?seconds=0.05')
        assert code == 200 and body


class TestDeepProfile:
    def test_capture_writes_bounded_artifacts(self, tmp_path):
        root = str(tmp_path / 'profiles')
        out = deep_profile(seconds=0.02, trigger='test', out_dir=root)
        # py.folded always lands; a jax/ trace rides along only when a
        # backend is live (depends on what ran earlier in the process)
        assert 'py.folded' in out['artifacts']
        assert ('jax' in out['artifacts']) == out['jax_trace']
        assert os.path.isfile(os.path.join(out['dir'], 'py.folded'))
        assert os.path.basename(out['dir']).startswith('profile-test-')
        # seconds clamp floor
        assert out['seconds'] == 0.02

    def test_prune_keeps_newest(self, tmp_path):
        root = str(tmp_path / 'profiles')
        for _ in range(PROFILE_KEEP + 3):
            deep_profile(seconds=0.01, trigger='t', out_dir=root)
        kept = [e for e in os.listdir(root) if e.startswith('profile-')]
        assert len(kept) == PROFILE_KEEP

    def test_endpoint_and_env_dir(self, server, tmp_path, monkeypatch):
        monkeypatch.setenv('KTPU_PROFILE_DIR', str(tmp_path / 'p'))
        code, ctype, body = get(server, '/debug/profile?seconds=0.02')
        assert code == 200 and ctype.startswith('application/json')
        out = json.loads(body)
        assert out['trigger'] == 'manual'
        assert out['dir'].startswith(str(tmp_path / 'p'))
        code, _, _ = get(server, '/debug/profile?seconds=nope')
        assert code == 400


class TestRouteRegistry:
    def test_index_served_at_debug_root(self, server):
        for path in ('/debug', '/debug/'):
            code, _, body = get(server, path)
            assert code == 200
            for route in routes():
                assert route in body

    def test_unknown_debug_path_404s_with_index(self, server):
        code, _, body = get(server, '/debug/nope')
        assert code == 404
        assert 'not found' in body
        assert '/debug/slo' in body  # the index rides the 404

    def test_unknown_non_debug_path_is_plain_404(self, server):
        code, _, body = get(server, '/nope')
        assert code == 404 and 'debug endpoints' not in body

    def test_trailing_slash_is_equivalent(self, server):
        a = get(server, '/debug/pprof')
        b = get(server, '/debug/pprof/')
        assert a == b

    def test_readme_table_does_not_drift(self):
        """The README endpoint table is generated
        (`scripts/analyze.py --debug-table`); every generated row must
        appear verbatim — a route added without regenerating fails."""
        table = render_debug_table()
        readme = open(os.path.join(REPO, 'README.md'),
                      encoding='utf-8').read()
        for line in table.splitlines():
            assert line in readme, f'README debug table drifted: {line}'
        assert render_debug_index().startswith('debug endpoints:')


class TestDataRoutes:
    def test_metrics_content_type_and_body(self, server):
        reg = MetricsRegistry()
        reg.inc('kyverno_tpu_scan_backpressure_seconds_total',
                0.5, stage='encode')
        from kyverno_tpu.observability.metrics import (global_registry,
                                                       set_global_registry)
        prev = global_registry()
        set_global_registry(reg)
        try:
            code, ctype, body = get(server, '/metrics')
        finally:
            set_global_registry(prev)
        assert code == 200
        assert ctype == 'text/plain; version=0.0.4'
        assert 'kyverno_tpu_scan_backpressure_seconds_total' in body

    def test_executables_route_disabled_then_live(self, server):
        code, _, body = get(server, '/debug/executables')
        assert code == 200 and json.loads(body) == {'enabled': False}
        executables.configure(registry=MetricsRegistry(), ledger_n=8)
        executables.record_build('k1', fingerprint='f1', capacity=64,
                                 source='aot_load', build_s=0.5)
        code, _, body = get(server, '/debug/executables')
        out = json.loads(body)
        assert out['enabled'] is True
        assert out['census']['by_source'] == {'aot_load': 1}
        code, ctype, body = get(server,
                                '/debug/executables?format=table')
        assert code == 200 and ctype.startswith('text/plain')
        assert 'aot_load' in body and 'KEY' in body

    def test_slo_route_disabled_then_live(self, server):
        code, _, body = get(server, '/debug/slo')
        assert code == 200 and json.loads(body) == {'enabled': False}
        slo.configure(registry=MetricsRegistry(), window_s=60.0,
                      p99_ms=100.0, target=0.9)
        slo.record('batch', 0.005)
        code, _, body = get(server, '/debug/slo')
        out = json.loads(body)
        assert out['enabled'] is True
        assert out['paths']['batch']['count'] == 1

    def test_timeline_route_disabled_then_live(self, server):
        from kyverno_tpu.observability import timeline
        timeline.disable()
        code, _, body = get(server, '/debug/timeline')
        assert code == 200 and json.loads(body) == {'enabled': False}
        timeline.configure(max_events=64)
        try:
            tl = timeline.begin_scan()
            t0 = tl.t0
            tl.record('encode', 0, t0, t0 + 0.01)
            tl.record('device_eval', 0, t0 + 0.01, t0 + 0.03)
            timeline.finish_scan(tl)
            code, _, body = get(server, '/debug/timeline')
            out = json.loads(body)
            assert out['enabled'] is True and out['scans'] == 1
            assert out['last']['bound_by'] == 'device_eval'
            assert out['blame_totals_s']
            assert out['summaries']
            code, ctype, body = get(server,
                                    '/debug/timeline?format=chrome')
            assert code == 200 and ctype.startswith('application/json')
            trace = json.loads(body)
            assert timeline.validate_chrome_trace(trace) == []
            assert trace['traceEvents']
        finally:
            timeline.disable()

    def test_concurrent_gets(self, server):
        """The threading server answers parallel requests — a slow
        sampling profile must not block the index."""
        results = []

        def fetch(path):
            results.append(get(server, path))

        threads = [threading.Thread(
            target=fetch, args=(p,), daemon=True) for p in (
                '/debug/pprof/profile?seconds=0.3',
                '/debug/', '/metrics', '/debug/pprof/goroutine',
                '/debug/slo')]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15)
        assert len(results) == 5
        assert all(code in (200,) for code, _, _ in results)
        assert time.monotonic() - t0 < 10.0
