import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.engine.api import PolicyContext, RuleStatus
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.engine.mutate.jsonpatch import (apply_patch, generate_patches,
                                                 load_patches)
from kyverno_tpu.engine.mutate.strategic import (apply_strategic_merge_patch,
                                                 strategic_merge)


class TestJsonPatch:
    def test_add_replace_remove(self):
        doc = {'a': 1, 'b': {'c': [1, 2]}}
        out = apply_patch(doc, [
            {'op': 'add', 'path': '/d', 'value': 9},
            {'op': 'replace', 'path': '/a', 'value': 2},
            {'op': 'remove', 'path': '/b/c/0'},
        ])
        assert out == {'a': 2, 'b': {'c': [2]}, 'd': 9}
        assert doc == {'a': 1, 'b': {'c': [1, 2]}}  # original untouched

    def test_append(self):
        out = apply_patch({'l': [1]}, [{'op': 'add', 'path': '/l/-', 'value': 2}])
        assert out == {'l': [1, 2]}

    def test_move_copy_test(self):
        out = apply_patch({'a': 1}, [
            {'op': 'copy', 'from': '/a', 'path': '/b'},
            {'op': 'test', 'path': '/b', 'value': 1},
            {'op': 'move', 'from': '/a', 'path': '/c'},
        ])
        assert out == {'b': 1, 'c': 1}

    def test_escaped_pointer(self):
        out = apply_patch({'metadata': {'annotations': {}}}, [
            {'op': 'add', 'path': '/metadata/annotations/example.com~1key',
             'value': 'v'}])
        assert out['metadata']['annotations']['example.com/key'] == 'v'

    def test_yaml_patch_text(self):
        ops = load_patches("- op: add\n  path: /x\n  value: 1\n")
        assert apply_patch({}, ops) == {'x': 1}

    def test_diff_roundtrip(self):
        a = {'x': 1, 'l': [1, 2, 3], 'm': {'k': 'v'}}
        b = {'x': 2, 'l': [1, 9], 'm': {'k': 'v', 'n': True}}
        ops = generate_patches(a, b)
        assert apply_patch(a, ops) == b


class TestStrategicMerge:
    def test_map_merge(self):
        base = {'metadata': {'labels': {'a': '1'}}}
        patch = {'metadata': {'labels': {'b': '2'}}}
        assert strategic_merge(base, patch) == {
            'metadata': {'labels': {'a': '1', 'b': '2'}}}

    def test_null_deletes(self):
        out = strategic_merge({'a': 1, 'b': 2}, {'a': None})
        assert out == {'b': 2}

    def test_containers_merge_by_name(self):
        base = {'spec': {'containers': [
            {'name': 'app', 'image': 'nginx:1'},
            {'name': 'sidecar', 'image': 'envoy:1'}]}}
        patch = {'spec': {'containers': [
            {'name': 'app', 'imagePullPolicy': 'Always'}]}}
        out = strategic_merge(base, patch)
        containers = out['spec']['containers']
        assert containers[0] == {'name': 'app', 'image': 'nginx:1',
                                 'imagePullPolicy': 'Always'}
        assert containers[1]['name'] == 'sidecar'

    def test_scalar_list_replaced(self):
        out = strategic_merge({'l': [1, 2]}, {'l': [9]})
        assert out == {'l': [9]}

    def test_patch_delete_directive(self):
        base = {'spec': {'containers': [{'name': 'a'}, {'name': 'b'}]}}
        patch = {'spec': {'containers': [{'name': 'a', '$patch': 'delete'}]}}
        out = strategic_merge(base, patch)
        assert out['spec']['containers'] == [{'name': 'b'}]

    def test_conditional_anchor_applies(self):
        # set imagePullPolicy only where image is nginx:*
        base = {'spec': {'containers': [
            {'name': 'a', 'image': 'nginx:1'},
            {'name': 'b', 'image': 'redis:7'}]}}
        overlay = {'spec': {'containers': [
            {'(image)': 'nginx:*', 'imagePullPolicy': 'IfNotPresent'}]}}
        out = apply_strategic_merge_patch(base, overlay)
        by_name = {c['name']: c for c in out['spec']['containers']}
        assert by_name['a'].get('imagePullPolicy') == 'IfNotPresent'
        assert 'imagePullPolicy' not in by_name['b']

    def test_conditional_anchor_map_skips(self):
        base = {'spec': {'hostNetwork': False}}
        overlay = {'spec': {'(hostNetwork)': True, 'dnsPolicy': 'Default'}}
        out = apply_strategic_merge_patch(base, overlay)
        assert out == base  # condition failed → no change

    def test_add_if_not_present(self):
        base = {'metadata': {'labels': {'a': '1'}}}
        overlay = {'metadata': {'labels': {'+(a)': 'X', '+(b)': '2'}}}
        out = apply_strategic_merge_patch(base, overlay)
        assert out['metadata']['labels'] == {'a': '1', 'b': '2'}


MUTATE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: add-labels
  annotations:
    pod-policies.kyverno.io/autogen-controllers: none
spec:
  rules:
    - name: add-team-label
      match:
        any:
          - resources:
              kinds: [Pod]
      mutate:
        patchStrategicMerge:
          metadata:
            labels:
              +(team): default-team
"""

MUTATE_JSON6902 = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: set-replicas
  annotations:
    pod-policies.kyverno.io/autogen-controllers: none
spec:
  rules:
    - name: bump
      match:
        any:
          - resources:
              kinds: [Deployment]
      mutate:
        patchesJson6902: |-
          - op: replace
            path: /spec/replicas
            value: 3
"""

MUTATE_FOREACH = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: prepend-registry
  annotations:
    pod-policies.kyverno.io/autogen-controllers: none
spec:
  rules:
    - name: prepend-registry-containers
      match:
        any:
          - resources:
              kinds: [Pod]
      mutate:
        foreach:
          - list: "request.object.spec.containers"
            patchesJson6902: |-
              - op: replace
                path: /spec/containers/{{elementIndex}}/image
                value: "registry.io/{{ element.image }}"
"""


def run_mutate(policy_yaml, resource):
    policy = Policy(yaml.safe_load(policy_yaml))
    pctx = PolicyContext(policy, new_resource=resource)
    return Engine().mutate(pctx)


class TestEngineMutate:
    def test_strategic_merge_add_label(self):
        resp = run_mutate(MUTATE_POLICY, {
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'namespace': 'default'},
            'spec': {'containers': [{'name': 'c', 'image': 'x'}]}})
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.PASS
        assert resp.patched_resource['metadata']['labels'] == {
            'team': 'default-team'}
        assert any(p['path'] == '/metadata/labels' for p in r.patches)

    def test_existing_label_untouched(self):
        resp = run_mutate(MUTATE_POLICY, {
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'namespace': 'default',
                         'labels': {'team': 'infra'}},
            'spec': {'containers': [{'name': 'c', 'image': 'x'}]}})
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.SKIP  # no patches → skip
        assert resp.patched_resource['metadata']['labels'] == {'team': 'infra'}

    def test_json6902(self):
        resp = run_mutate(MUTATE_JSON6902, {
            'apiVersion': 'apps/v1', 'kind': 'Deployment',
            'metadata': {'name': 'd', 'namespace': 'default'},
            'spec': {'replicas': 1}})
        assert resp.policy_response.rules[0].status == RuleStatus.PASS
        assert resp.patched_resource['spec']['replicas'] == 3

    def test_foreach_mutation(self):
        resp = run_mutate(MUTATE_FOREACH, {
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'namespace': 'default'},
            'spec': {'containers': [
                {'name': 'a', 'image': 'nginx:1'},
                {'name': 'b', 'image': 'redis:7'}]}})
        r = resp.policy_response.rules[0]
        assert r.status == RuleStatus.PASS
        images = [c['image'] for c in resp.patched_resource['spec']['containers']]
        assert images == ['registry.io/nginx:1', 'registry.io/redis:7']

    def test_mutate_then_validate_consistency(self):
        # the patched resource re-enters the JSON context
        resp = run_mutate(MUTATE_POLICY, {
            'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': 'p', 'namespace': 'default'},
            'spec': {'containers': [{'name': 'c', 'image': 'x'}]}})
        assert resp.patched_resource['metadata']['labels']['team'] == 'default-team'


class TestNoDeepcopyApplier:
    """PR-8 satellite: the host strategic-merge applier dropped its
    per-(resource, element) deepcopies (the '10-20x more host work'
    note).  Pins the two properties that made that safe: preprocessing
    never mutates the rule-constant overlay, and the output is
    identical to a deepcopy-based reference applier."""

    OVERLAY = {
        'metadata': {'labels': {'+(team)': 'default', 'stage': 'prod'},
                     'annotations': {'owner': 'core'}},
        'spec': {
            'dnsPolicy': 'ClusterFirst',
            'containers': [{
                '(name)': '*',
                'securityContext': {'+(runAsNonRoot)': True},
            }],
        },
    }

    def _docs(self):
        return [
            {'apiVersion': 'v1', 'kind': 'Pod',
             'metadata': {'name': 'a'},
             'spec': {'containers': [{'name': 'c1', 'image': 'nginx'}]}},
            {'apiVersion': 'v1', 'kind': 'Pod',
             'metadata': {'name': 'b', 'labels': {'team': 'blue'}},
             'spec': {'containers': [
                 {'name': 'c1', 'image': 'nginx',
                  'securityContext': {'runAsNonRoot': False}},
                 {'name': 'c2', 'image': 'redis'}]}},
            {'apiVersion': 'v1', 'kind': 'Pod',
             'metadata': {'name': 'c', 'labels': {'stage': 'dev'}},
             'spec': {'containers': [], 'dnsPolicy': 'Default'}},
        ]

    def test_overlay_never_mutated_across_resources(self):
        import copy
        import json
        overlay = copy.deepcopy(self.OVERLAY)
        pin = json.dumps(overlay, sort_keys=True)
        for doc in self._docs():
            apply_strategic_merge_patch(copy.deepcopy(doc), overlay)
            assert json.dumps(overlay, sort_keys=True) == pin

    def test_base_never_mutated(self):
        import copy
        import json
        for doc in self._docs():
            base = copy.deepcopy(doc)
            pin = json.dumps(base, sort_keys=True)
            apply_strategic_merge_patch(base, self.OVERLAY)
            assert json.dumps(base, sort_keys=True) == pin

    def test_output_identical_to_deepcopy_reference(self):
        """The reference applier deepcopies overlay and base per call —
        exactly what the applier did before the copy-on-write change."""
        import copy
        from kyverno_tpu.engine.mutate.strategic import (
            ConditionError, GlobalConditionError, preprocess_pattern)

        def reference(base, overlay):
            overlay = copy.deepcopy(overlay)
            try:
                overlay = preprocess_pattern(overlay,
                                             copy.deepcopy(base))
            except (ConditionError, GlobalConditionError):
                return copy.deepcopy(base)
            return strategic_merge(copy.deepcopy(base), overlay)

        for doc in self._docs():
            got = apply_strategic_merge_patch(copy.deepcopy(doc),
                                              self.OVERLAY)
            want = reference(doc, self.OVERLAY)
            assert got == want

    def test_engine_rule_output_identical_to_reference(self):
        """Whole-rule check through the engine loop: same responses and
        patched doc as a deepcopy of the same policy applied the old
        way (fresh Policy objects per run, so no state can leak)."""
        import copy
        import json
        policy_doc = {
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 'p'},
            'spec': {'rules': [{
                'name': 'r',
                'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                'mutate': {'patchStrategicMerge': self.OVERLAY}}]}}
        engine = Engine()
        for doc in self._docs():
            outs = []
            for policy in (Policy(copy.deepcopy(policy_doc)),
                           Policy(copy.deepcopy(policy_doc))):
                pctx = PolicyContext(
                    policy, new_resource=copy.deepcopy(doc))
                er = engine.mutate(pctx)
                outs.append((
                    [(r.name, str(r.status), r.message, r.patches)
                     for r in er.policy_response.rules],
                    json.dumps(er.patched_resource, sort_keys=True)))
            assert outs[0] == outs[1]
