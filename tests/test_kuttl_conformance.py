"""Replay of the reference kuttl conformance corpus
(/root/reference/test/conformance/kuttl — SURVEY.md §4) through the
in-memory cluster + real daemons (kyverno_tpu/conformance/kuttl.py).
Suites are consumed IN PLACE from the read-only reference checkout —
nothing is vendored.

Every case directory in the corpus is parametrized; directories the
hermetic environment cannot replay are listed in DIVERGENT with the
reason and skipped explicitly — never silently."""

import os

import pytest

from kyverno_tpu.conformance.kuttl import (KuttlFailure, Unsupported,
                                           run_suite)

ROOT = '/root/reference/test/conformance/kuttl'

#: suites this environment cannot replay, with reasons (zero-egress
#: sandbox: no live registry; no kubelet: no exec/eviction; the
#: harness does not execute arbitrary shell scripts)
DIVERGENT = {
    # live-cluster shell scripts
    'mutate/clusterpolicy/standard/existing/mutate-existing-node-status':
        'modifies the controller resource filters + node status via '
        'shell scripts against a live node',
    'mutate/clusterpolicy/standard/mutate-node-status':
        'modifies node status via shell scripts against a live node',
    'mutate/clusterpolicy/standard/userInfo-roles-clusterRoles':
        'creates client certificates against a live cluster CA',
    'validate/clusterpolicy/standard/enforce/api-initiated-pod-eviction':
        'drives the eviction subresource via a shell script',
    'validate/clusterpolicy/standard/enforce/block-pod-exec-requests':
        'kubectl exec against a live kubelet',
    # network-bound image verification (zero-egress sandbox; the
    # signature *crypto* is covered offline by tests/test_cosign_crypto)
    'validate/e2e/trusted-images':
        'imageData context entry needs a live registry',
    'verifyImages/clusterpolicy/standard/imageExtractors-complex':
        'verifies live ghcr.io signatures',
    'verifyImages/clusterpolicy/standard/imageExtractors-simple':
        'verifies live ghcr.io signatures',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-1':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-2':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-3':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-4':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-counts-1':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-counts-2':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-attestations-multiple-subjects-counts-3':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-mutatedigest-verifydigest-required':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-nomutatedigest-noverifydigest-norequired':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'keyless-nomutatedigest-noverifydigest-required':
        'keyless verification against the public Fulcio/Rekor instances',
    'verifyImages/clusterpolicy/standard/'
    'mutateDigest-noverifyDigest-norequired':
        'digest mutation resolves tags against a live registry',
    'verifyImages/clusterpolicy/standard/noconfigmap-diffimage-success':
        'verifies live ghcr.io signatures',
    'verifyImages/clusterpolicy/standard/'
    'nomutateDigest-verifyDigest-norequired':
        'verifies live ghcr.io signatures',
}


def _case_dirs():
    cases = []
    for dirpath, _dirnames, filenames in os.walk(ROOT):
        rel = os.path.relpath(dirpath, ROOT)
        if rel.startswith('_aaa'):
            continue
        if any(f[0].isdigit() and f.endswith('.yaml') for f in filenames):
            cases.append(rel)
    return sorted(cases)


CASES = _case_dirs()


def test_corpus_discovered():
    """The corpus walk must keep finding the reference suites."""
    assert len(CASES) >= 100, CASES


def test_divergent_paths_exist():
    missing = [rel for rel in DIVERGENT
               if not os.path.isdir(os.path.join(ROOT, rel))]
    assert not missing, f'divergence list drifted: {missing}'


@pytest.mark.parametrize('rel', CASES)
def test_kuttl_suite(rel):
    if rel in DIVERGENT:
        pytest.skip(f'divergent: {DIVERGENT[rel]}')
    try:
        run_suite(os.path.join(ROOT, rel))
    except Unsupported as e:
        pytest.fail(f'unsupported kuttl feature (not divergence-listed): '
                    f'{e}')
    except KuttlFailure as e:
        raise AssertionError(f'{rel}: {e}') from e
