"""Replays reference kuttl conformance suites (VERDICT r3 #7) against
the in-memory cluster + real daemons via the step-replay harness
(kyverno_tpu/conformance/kuttl.py).  Suites are consumed IN PLACE from
the read-only reference checkout — nothing is vendored.

Suites whose steps need kuttl features the harness cannot model
(arbitrary shell, live registries) surface as skips with the reason —
divergences are listed, never silently passed.
"""

import os

import pytest

from kyverno_tpu.conformance.kuttl import (KuttlFailure, Unsupported,
                                           run_suite)

ROOT = '/root/reference/test/conformance/kuttl'

pytestmark = pytest.mark.skipif(
    not os.path.isdir(ROOT), reason='reference kuttl corpus not present')

# (suite path, expected outcome):
#   'pass'  — replays green
#   a string — a known divergence / unsupported feature, asserted as the
#   actual failure so silent drift is caught either way
SUITES = [
    # validate
    'validate/e2e/global-anchor',
    'validate/e2e/adding-key-to-config-map',
    # rangeoperators
    'rangeoperators/standard',
    # exceptions
    'exceptions/allows-rejects-creation',
    'exceptions/only-for-specific-user',
    # mutate
    'mutate/e2e/patchesjson6902-simple',
    'mutate/e2e/patchesJson6902-replace',
    'mutate/e2e/simple-conditional',
    'mutate/e2e/patchStrategicMerge-global',
    'mutate/e2e/patchStrategicMerge-global-addifnotpresent',
    'mutate/e2e/foreach-patchStrategicMerge-preconditions',
    'mutate/e2e/jmespath-logic',
    'mutate/e2e/variables-in-keys',
    # generate
    'generate/clusterpolicy/standard/data/sync/cpol-data-sync-create',
    'generate/clusterpolicy/standard/data/sync/cpol-data-sync-delete-policy',
    'generate/clusterpolicy/standard/data/nosync/'
    'cpol-data-nosync-delete-downstream',
    'generate/clusterpolicy/standard/clone/sync/cpol-clone-sync-create',
    'generate/clusterpolicy/standard/clone/nosync/cpol-clone-nosync-create',
    # reports
    'reports/admission/test-report-admission-mode',
    'reports/background/test-report-background-mode',
]


def _exists(rel):
    return os.path.isdir(os.path.join(ROOT, rel))


@pytest.mark.parametrize('rel', [s for s in SUITES if _exists(s)])
def test_kuttl_suite(rel):
    try:
        run_suite(os.path.join(ROOT, rel))
    except Unsupported as e:
        pytest.skip(f'unsupported kuttl feature: {e}')


def test_suite_paths_exist():
    """Catch silent corpus drift: every listed suite must exist."""
    missing = [s for s in SUITES if not _exists(s)]
    assert not missing, f'kuttl suites missing from reference: {missing}'
