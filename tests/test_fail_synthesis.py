"""Device FAIL-message synthesis vs the host engine.

The evaluator's third output (``fdet``) identifies the walk position the
host would report for each FAIL; the scanner re-builds the exact
``validation error: … failed at path …`` message from compile-time
templates (reference formats: pkg/engine/validation.go:722
buildErrorMessage, :746 buildAnyPatternErrorMessage, :460 getDenyMessage).
These tests assert bit-identical messages against a pure host run across
the tricky walk shapes: array-of-maps element indices, parent-path ``*``
shortcuts, anchors, anyPattern multi-child messages, foreach deny fails,
and message-dot/empty/variable corner cases.
"""

import random

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: elem-paths
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: image-tag
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "An image tag is required"
        pattern:
          spec:
            containers:
              - image: "!*:latest"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: nested-elem
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: no-host-ports
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "host ports are forbidden."
        pattern:
          spec:
            containers:
              - ports:
                  - hostPort: 0
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: star-parent-path
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: require-requests
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: no message dot here
        pattern:
          spec:
            containers:
              - resources:
                  requests:
                    memory: "?*"
                    cpu: "*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: no-message
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: empty-msg-rule
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        pattern:
          metadata:
            labels:
              app: "?*"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: anchors
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: no-host-network-when-labeled
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "hostNetwork must be false for labeled pods."
        pattern:
          spec:
            =(hostNetwork): false
    - name: negation-host-pid
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "hostPID is not allowed"
        pattern:
          spec:
            X(hostPID): "null"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: any-pattern-msgs
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: run-as-nonroot
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: >-
          Running as root is not allowed. The fields
          spec.securityContext.runAsNonRoot must be true.
        anyPattern:
          - spec:
              securityContext:
                runAsNonRoot: true
          - spec:
              containers:
                - securityContext:
                    runAsNonRoot: true
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: foreach-caps
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: drop-all-caps
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: Containers must drop ALL capabilities.
        foreach:
          - list: request.object.spec.containers[]
            deny:
              conditions:
                all:
                  - key: ALL
                    operator: AnyNotIn
                    value: "{{ element.securityContext.capabilities.drop[] || '' }}"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: variable-message
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: var-msg-rule
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "pod {{request.object.metadata.name}} must set app"
        pattern:
          metadata:
            labels:
              app: "?*"
"""


def load_pack():
    return [Policy(d) for d in yaml.safe_load_all(PACK) if d]


def make_pod(rng):
    containers = []
    for i in range(rng.randint(1, 3)):
        c = {'name': f'c{i}',
             'image': rng.choice(['nginx:latest', 'nginx:1.25', 'app',
                                  'ghcr.io/x/y:v1'])}
        if rng.random() < 0.6:
            c['resources'] = {'requests': {
                k: v for k, v in
                [('memory', '64Mi'), ('cpu', '100m')][:rng.randint(0, 2)]}}
        if rng.random() < 0.5:
            sc = {}
            if rng.random() < 0.6:
                sc['runAsNonRoot'] = rng.random() < 0.5
            if rng.random() < 0.5:
                sc['capabilities'] = {'drop': rng.choice(
                    [['ALL'], ['KILL'], [], ['ALL', 'KILL']])}
            c['securityContext'] = sc
        if rng.random() < 0.4:
            c['ports'] = [{'containerPort': 80,
                           'hostPort': rng.choice([0, 80, 9000])}
                          for _ in range(rng.randint(1, 2))]
        containers.append(c)
    pod = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': f'p{rng.randint(0, 999)}',
                        'namespace': 'default'},
           'spec': {'containers': containers}}
    if rng.random() < 0.4:
        pod['metadata']['labels'] = rng.choice(
            [{'app': 'x'}, {'app': ''}, {'other': 'y'}])
    if rng.random() < 0.3:
        pod['spec']['hostNetwork'] = rng.choice([True, False])
    if rng.random() < 0.3:
        pod['spec']['hostPID'] = True
    if rng.random() < 0.3:
        pod['spec']['securityContext'] = {
            'runAsNonRoot': rng.random() < 0.5}
    return pod


def host_results(engine, policies, resource):
    host = {}
    for policy in policies:
        resp = engine.apply_background_checks(
            PolicyContext(policy, new_resource=resource))
        if resp.policy_response.rules:
            host[policy.name] = {r.name: (r.status, r.message)
                                 for r in resp.policy_response.rules}
    return host


class TestFailSynthesis:
    def test_sites_compiled(self):
        scanner = BatchScanner(load_pack())
        by_name = {p.rule_name: p for p in scanner.cps.programs}
        assert by_name['image-tag'].fail_sites is not None
        assert by_name['image-tag'].fail_prefix is not None
        assert by_name['no-host-ports'].fail_sites is not None
        assert by_name['run-as-nonroot'].any_fail_sites is not None
        assert by_name['drop-all-caps'].deny_fail_message == \
            'validation failure: Containers must drop ALL capabilities.'
        # variable messages cannot be synthesized
        assert by_name['var-msg-rule'].fail_sites is None
        assert by_name['var-msg-rule'].fail_prefix is None

    def test_path_templates(self):
        scanner = BatchScanner(load_pack())
        by_name = {p.rule_name: p for p in scanner.cps.programs}
        assert '/spec/containers/{e0}/image/' in by_name['image-tag'].fail_sites
        assert '/spec/containers/{e0}/ports/{e1}/hostPort/' in \
            by_name['no-host-ports'].fail_sites
        # the map-level '*' shortcut reports the PARENT path
        assert '/spec/containers/{e0}/resources/requests/' in \
            by_name['require-requests'].fail_sites

    def test_device_vs_host_messages_fuzz(self):
        policies = load_pack()
        engine = Engine()
        rng = random.Random(7)
        resources = [make_pod(rng) for _ in range(200)]
        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)
        for resource, responses in zip(resources, scanned):
            host = host_results(engine, policies, resource)
            got = {}
            for er in responses:
                if er.policy_response.rules:
                    got[er.policy_response.policy_name] = {
                        r.name: (r.status, r.message)
                        for r in er.policy_response.rules}
            assert got == host, f'divergence on {resource}'

    def test_synthesis_actually_used(self):
        """The fuzz above must exercise synthesized FAILs, not just fall
        back to host materialization for everything."""
        policies = load_pack()
        rng = random.Random(7)
        resources = [make_pod(rng) for _ in range(200)]
        scanner = BatchScanner(policies)
        calls = [0]
        inner = scanner._materialize

        def counting(prog, doc):
            calls[0] += 1
            return inner(prog, doc)
        scanner._materialize = counting
        out = scanner.scan(resources)
        decisions = sum(len(r.policy_response.rules)
                        for rs in out for r in rs)
        fails = sum(1 for rs in out for r in rs
                    for x in r.policy_response.rules if x.status == 'fail')
        assert fails > 100, 'fuzz produced too few FAILs to be meaningful'
        # only the variable-message rule's fails need the host
        assert calls[0] < fails / 2, \
            f'{calls[0]} materializations for {fails} fails: synthesis idle'
