"""Executable lifecycle ledger (ISSUE 14 tentpole a).

Every compiled program gets a lifecycle record: acquisition source
(fresh_compile | aot_load | persistent_xla), build cost, cumulative
dispatch/device-time accounting, eviction marking.  Pins the ledger
unit behavior, the metric gauges, the zero-duration lifecycle spans,
the scan-path bit-identity with the ledger off, and the second-process
AOT acceptance: a fresh process against a warm store registers its
executables as ``aot_load`` with zero fresh compiles.  CPU-only,
tier-1.
"""

import json
import os
import subprocess
import sys

from kyverno_tpu.observability import executables, tracing
from kyverno_tpu.observability.executables import (EXEC_COUNT,
                                                   EXEC_DEVICE_SECONDS,
                                                   EXEC_DISPATCHES,
                                                   ExecutableLedger)
from kyverno_tpu.observability.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


import pytest


@pytest.fixture(autouse=True)
def _clean_modules():
    yield
    executables.disable()
    tracing.disable()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestLedger:
    def test_build_dispatch_evict_roundtrip(self):
        reg = MetricsRegistry()
        led = ExecutableLedger(8, registry=reg, now=FakeClock())
        led.record_build('k1', fingerprint='f' * 20, capacity=64,
                         source='fresh_compile', build_s=2.5)
        led.record_dispatch('k1', 0.25)
        led.record_dispatch('k1', 0.25)
        rec = led.records()[0]
        assert rec.dispatches == 2
        assert abs(rec.device_s - 0.5) < 1e-9
        assert reg.gauge_value(EXEC_COUNT, source='fresh_compile') == 1.0
        assert reg.counter_value(EXEC_DISPATCHES,
                                 source='fresh_compile') == 2.0
        assert abs(reg.counter_value(EXEC_DEVICE_SECONDS,
                                     source='fresh_compile') - 0.5) < 1e-9
        led.record_eviction('k1', 'execute_failed')
        rec = led.records()[0]
        assert rec.evicted and rec.evict_reason == 'execute_failed'
        # evicted records leave the live gauge but stay in the table
        assert reg.gauge_value(EXEC_COUNT, source='fresh_compile') == 0.0
        assert led.report()['executables'][0]['evicted'] is True

    def test_unknown_key_dispatch_and_eviction_are_noops(self):
        led = ExecutableLedger(8, registry=None)
        led.record_dispatch('nope', 1.0)
        led.record_eviction('nope', 'whatever')
        assert led.records() == []

    def test_lru_bound(self):
        led = ExecutableLedger(2, registry=None)
        for k in ('a', 'b', 'c'):
            led.record_build(k, source='fresh_compile')
        keys = [r.key for r in led.records()]
        assert keys == ['b', 'c']
        # a dispatch refreshes recency: 'b' survives the next insert
        led.record_dispatch('b', 0.1)
        led.record_build('d', source='fresh_compile')
        assert [r.key for r in led.records()] == ['b', 'd']

    def test_reacquisition_keeps_dispatch_history(self):
        led = ExecutableLedger(8, registry=None)
        led.record_build('k', source='fresh_compile', build_s=3.0)
        led.record_dispatch('k', 0.5)
        led.record_build('k', source='aot_load', build_s=0.2)
        rec = led.records()[0]
        assert rec.source == 'aot_load'
        assert rec.build_s == 0.2
        assert rec.dispatches == 1  # cumulative history survives

    def test_census_and_report(self):
        led = ExecutableLedger(8, registry=None)
        led.record_build('k1', source='fresh_compile', build_s=2.0)
        led.record_build('k2', source='aot_load', build_s=0.5)
        led.record_dispatch('k1', 0.125)
        led.record_eviction('k2', 'feature_mismatch')
        c = led.census()
        assert c['live'] == 1
        assert c['by_source'] == {'fresh_compile': 1}
        assert c['dispatches'] == 1
        # evicted records drop out of the live build_s sum
        assert abs(c['build_s'] - 2.0) < 1e-9
        rep = led.report()
        assert rep['enabled'] is True and rep['capacity'] == 8
        assert len(rep['executables']) == 2
        table = led.render_table()
        assert 'fresh_compile' in table
        assert 'evicted:feature_mismatch' in table

    def test_cost_analysis_shapes(self):
        class Compiled:
            def cost_analysis(self):
                return [{'flops': 12.0, 'bytes accessed': 34.0}]

        class Broken:
            def cost_analysis(self):
                raise RuntimeError('no backend')

        assert executables.cost_analysis(Compiled()) == {
            'flops': 12.0, 'bytes_accessed': 34.0}
        assert executables.cost_analysis(Broken()) == {}

    def test_lifecycle_events_ride_the_tracer(self):
        exporter = tracing.configure()
        led = ExecutableLedger(8, registry=None)
        led.record_build('k1', fingerprint='abc', capacity=64,
                         source='aot_load', build_s=0.7)
        led.record_eviction('k1', 'execute_failed')
        names = [s.name for s in exporter.spans()]
        assert names == ['kyverno/executable/build',
                         'kyverno/executable/evict']
        evict = exporter.spans()[-1]
        assert evict.attributes['evict_reason'] == 'execute_failed'
        assert evict.attributes['source'] == 'aot_load'
        # zero-duration: the span ends at start (lifecycle event, not
        # a timed region)
        assert evict.end_ns >= evict.start_ns


class TestModuleState:
    def test_noop_until_configured(self):
        assert not executables.enabled()
        executables.record_build('k', source='fresh_compile')
        executables.record_dispatch('k', 1.0)
        executables.record_eviction('k', 'x')
        assert executables.census() == {}

    def test_env_zero_disables(self, monkeypatch):
        monkeypatch.setenv('KTPU_EXEC_LEDGER_N', '0')
        assert executables.configure() is None
        assert not executables.enabled()

    def test_configure_roundtrip(self):
        led = executables.configure(registry=MetricsRegistry(),
                                    ledger_n=4)
        assert executables.enabled() and executables.ledger() is led
        executables.record_build('k', source='persistent_xla')
        assert executables.census()['live'] == 1
        executables.disable()
        assert executables.census() == {}


# -- second-process AOT acceptance -------------------------------------------
#
# A fresh process against a warm AOT store must register every
# executable as source=aot_load with ZERO fresh compiles — the ledger
# is how a cache regression becomes visible.  Single canonical
# capacity (row counts 1 and 63 both pad to the small capacity 64) so
# the probe pays one compile, and the census stays inside the bench's
# WARM_EXECUTABLES_MAX=2 budget.

_PROBE_SCRIPT = r'''
import json
from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import executables
from kyverno_tpu.observability.metrics import MetricsRegistry

POLICY = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'require-labels', 'annotations': {
        'pod-policies.kyverno.io/autogen-controllers': 'none'}},
    'spec': {'validationFailureAction': 'Enforce', 'rules': [
        {'name': 'check-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'app label required',
                      'pattern': {'metadata': {'labels': {'app': '?*'}}}}},
    ]}}


def pod(i):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{i}', 'namespace': 'default',
                         'labels': {'app': 'x'} if i % 2 else {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}


executables.configure(registry=MetricsRegistry(), ledger_n=16)
from kyverno_tpu.compiler.scan import BatchScanner
scanner = BatchScanner([Policy(POLICY)])
rows = {}
for n in (1, 63):
    status, detail, match = scanner.scan_statuses(
        [pod(i) for i in range(n)])
    rows[str(n)] = status.tolist()
from kyverno_tpu.compiler import aot
aot.flush_stores()
print(json.dumps({'census': executables.census(), 'rows': rows}))
'''


def _run_probe(cache_dir, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'PYTHONPATH': REPO,
        'KTPU_SCAN_CHUNK': '256',
        'KTPU_SMALL_BATCH': '64',
        'KTPU_ENCODE_PROCS': '0',
        'KTPU_AOT': '1',
        'KTPU_AOT_CACHE_DIR': os.path.join(str(cache_dir), 'aot'),
        'KTPU_COMPILE_CACHE': os.path.join(str(cache_dir), 'xla'),
    })
    out = subprocess.run([sys.executable, '-c', _PROBE_SCRIPT],
                         env=env, cwd=REPO, capture_output=True,
                         text=True, timeout=timeout)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_second_process_census_is_all_aot_load(tmp_path):
    """ISSUE 14 acceptance: the ledger of a second AOT-warm process
    shows source=aot_load with zero fresh compiles, bit-identical
    statuses, and a census inside the WARM_EXECUTABLES_MAX=2 bench
    budget."""
    first = _run_probe(tmp_path)
    assert first['census']['live'] >= 1, first
    assert first['census']['live'] <= 2, first  # WARM_EXECUTABLES_MAX
    assert set(first['census']['by_source']) == {'fresh_compile'}, first
    second = _run_probe(tmp_path)
    assert second['census']['by_source'].get('fresh_compile', 0) == 0, \
        second
    assert second['census']['by_source'].get('aot_load', 0) >= 1, second
    assert second['census']['live'] <= 2, second
    assert second['census']['dispatches'] >= 2, second  # both scans
    assert second['rows'] == first['rows']
