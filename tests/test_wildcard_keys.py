"""Device compilation of wildcard pattern KEYS under metadata
labels/annotations (reference: pkg/engine/wildcards/wildcards.go:62
ExpandInMetadata — the restrict-apparmor-profiles shape).

The device resolves the first matching map key at encode time; FAIL
messages embed the resolved key, so they re-materialize on the host —
statuses and messages must stay bit-identical to the host engine.
"""

import random

import pytest

from kyverno_tpu.api.policy import Policy, load_policies_from_yaml
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

APPARMOR = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: restrict-apparmor-profiles
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  background: true
  rules:
    - name: app-armor
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: >-
          Specifying other AppArmor profiles is disallowed.
        pattern:
          =(metadata):
            =(annotations):
              =(container.apparmor.security.beta.kubernetes.io/*): "runtime/default | localhost/*"
"""

LABEL_WILD = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: team-label
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  background: true
  rules:
    - name: team-label
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "team-* labels must name a platform team"
        pattern:
          metadata:
            labels:
              team-*: "platform | infra"
"""

AA_KEY = 'container.apparmor.security.beta.kubernetes.io'


def pod(name, annotations=None, labels=None, spec=None):
    meta = {'name': name, 'namespace': 'default'}
    if annotations is not None:
        meta['annotations'] = annotations
    if labels is not None:
        meta['labels'] = labels
    return {'apiVersion': 'v1', 'kind': 'Pod', 'metadata': meta,
            'spec': spec or {'containers': [{'name': 'c', 'image': 'i'}]}}


def host_results(policies, docs):
    engine = Engine()
    out = []
    for doc in docs:
        row = {}
        for policy in policies:
            resp = engine.apply_background_checks(
                PolicyContext(policy, new_resource=doc))
            row[policy.name] = {
                r.name: (str(r.status), r.message)
                for r in resp.policy_response.rules}
        out.append(row)
    return out


def device_results(policies, docs):
    scanner = BatchScanner(policies)
    out = []
    for responses in scanner.scan(docs):
        row = {}
        for er in responses:
            row[er.policy_response.policy_name] = {
                r.name: (str(r.status), r.message)
                for r in er.policy_response.rules}
        out.append(row)
    return out, scanner


class TestWildcardKeyCompile:
    def test_apparmor_rule_compiles_to_device(self):
        policies = load_policies_from_yaml(APPARMOR)
        cps = compile_policies(policies)
        assert not cps.host_rules, \
            'wildcard-key apparmor rule must compile to the device'
        assert len(cps.programs) == 1

    def test_full_pack_zero_host_rules(self):
        """VERDICT r3 #9: the full best-practices+charts pack compiles
        with zero host rules (select-secrets' apiCall context keeps it
        host-side by design — it is the only permitted exception)."""
        import bench
        cps = compile_policies(bench.load_policy_pack())
        names = {r.get('name') for _, r, _ in cps.host_rules}
        assert all('app-armor' not in (n or '') for n in names), \
            f'apparmor rules still host-bound: {names}'

    def test_statuses_match_host(self):
        policies = load_policies_from_yaml(APPARMOR)
        docs = [
            pod('no-annotations'),
            pod('unrelated', annotations={'foo': 'bar'}),
            pod('ok-default', annotations={f'{AA_KEY}/c': 'runtime/default'}),
            pod('ok-localhost', annotations={f'{AA_KEY}/c': 'localhost/prof'}),
            pod('bad', annotations={f'{AA_KEY}/c': 'unconfined'}),
            pod('bad-second-key', annotations={
                'foo': 'bar', f'{AA_KEY}/x': 'unconfined'}),
            pod('first-match-wins', annotations={
                f'{AA_KEY}/a': 'runtime/default',
                f'{AA_KEY}/b': 'unconfined'}),
            pod('empty-annotations', annotations={}),
        ]
        host = host_results(policies, docs)
        dev, scanner = device_results(policies, docs)
        assert dev == host
        # sanity: the interesting rows actually exercise both outcomes
        assert host[4]['restrict-apparmor-profiles']['app-armor'][0] == 'fail'
        assert host[2]['restrict-apparmor-profiles']['app-armor'][0] == 'pass'

    def test_first_match_resolution_matches_host(self):
        """ExpandInMetadata picks the FIRST matching key in document
        order; later violating keys are invisible (host quirk kept)."""
        policies = load_policies_from_yaml(APPARMOR)
        doc = pod('first-wins', annotations={
            f'{AA_KEY}/a': 'runtime/default',
            f'{AA_KEY}/b': 'unconfined'})
        host = host_results(policies, [doc])
        dev, _ = device_results(policies, [doc])
        assert dev == host
        assert host[0]['restrict-apparmor-profiles']['app-armor'][0] == 'pass'

    def test_plain_wildcard_label_key(self):
        policies = load_policies_from_yaml(LABEL_WILD)
        cps = compile_policies(policies)
        assert not cps.host_rules
        docs = [
            pod('team-ok', labels={'team-a': 'platform'}),
            pod('team-bad', labels={'team-a': 'marketing'}),
            pod('no-match', labels={'app': 'x'}),
            pod('no-labels'),
        ]
        host = host_results(policies, docs)
        dev, _ = device_results(policies, docs)
        assert dev == host

    def test_fuzz_against_host(self):
        policies = load_policies_from_yaml(APPARMOR + '---\n' + LABEL_WILD)
        rng = random.Random(3)
        profiles = ['runtime/default', 'localhost/x', 'unconfined',
                    'docker/default', '']
        docs = []
        for i in range(200):
            annotations = {}
            labels = {}
            if rng.random() < 0.7:
                for k in range(rng.randint(0, 3)):
                    annotations[f'{AA_KEY}/c{k}'] = rng.choice(profiles)
            if rng.random() < 0.3:
                annotations['other/key'] = 'x'
            if rng.random() < 0.6:
                labels[f'team-{rng.randint(0, 2)}'] = rng.choice(
                    ['platform', 'infra', 'sales'])
            docs.append(pod(f'p{i}',
                            annotations=annotations or None,
                            labels=labels or None))
        host = host_results(policies, docs)
        dev, _ = device_results(policies, docs)
        assert dev == host

    def test_wildcard_outside_metadata_stays_host(self):
        yaml_doc = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: wild-spec
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: wild-spec
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        pattern:
          spec:
            node*: "worker-*"
"""
        cps = compile_policies(load_policies_from_yaml(yaml_doc))
        assert len(cps.host_rules) == 1

    def test_multi_key_map_stays_host(self):
        """Sibling ordering under resolved keys is data-dependent —
        maps with >1 key alongside a wildcard key stay on the host."""
        yaml_doc = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: two-keys
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: two-keys
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: m
        pattern:
          metadata:
            annotations:
              =(x-*): "a"
              other: "b"
"""
        cps = compile_policies(load_policies_from_yaml(yaml_doc))
        assert len(cps.host_rules) == 1
