import pytest

from kyverno_tpu.engine import jmespath as jp
from kyverno_tpu.engine.jmespath import JMESPathError


def s(expr, data):
    return jp.search(expr, data)


class TestCoreLanguage:
    def test_field_access(self):
        assert s('a', {'a': 1}) == 1
        assert s('a.b.c', {'a': {'b': {'c': 'x'}}}) == 'x'
        assert s('a.b', {'a': 1}) is None

    def test_missing_field_raises_not_found(self):
        # kyverno/go-jmespath fork behavior: a missing field is an error,
        # not null — this is what makes unresolved {{vars}} fail rules
        from kyverno_tpu.engine.jmespath import NotFoundError
        with pytest.raises(NotFoundError):
            s('missing', {'a': 1})
        with pytest.raises(NotFoundError):
            s('a.b.c', {'a': {}})
        # explicit null is NOT an error
        assert s('a', {'a': None}) is None
        # || rescues a missing field
        assert s("missing || 'default'", {'a': 1}) == 'default'

    def test_quoted_field(self):
        assert s('"app.kubernetes.io/name"', {'app.kubernetes.io/name': 'x'}) == 'x'
        assert s('a."b.c"', {'a': {'b.c': 2}}) == 2

    def test_index(self):
        assert s('[0]', [1, 2, 3]) == 1
        assert s('[-1]', [1, 2, 3]) == 3
        assert s('[5]', [1, 2]) is None
        assert s('a[1]', {'a': [1, 2]}) == 2

    def test_slice(self):
        assert s('[0:2]', [1, 2, 3]) == [1, 2]
        assert s('[::2]', [1, 2, 3, 4]) == [1, 3]
        assert s('[::-1]', [1, 2, 3]) == [3, 2, 1]

    def test_index_then_slice_projects(self):
        data = {'a': [[{'b': 1}, {'b': 2}, {'b': 3}]]}
        assert s('a[0][0:2].b', data) == [1, 2]

    def test_function_args_require_commas(self):
        import pytest as _pytest
        with _pytest.raises(JMESPathError):
            jp.compile("contains(@ 'a')")
        with _pytest.raises(JMESPathError):
            jp.compile('length(@,)')

    def test_projection(self):
        data = {'items': [{'n': 1}, {'n': 2}, {'x': 9}]}
        assert s('items[*].n', data) == [1, 2]

    def test_value_projection(self):
        assert sorted(s('*.n', {'a': {'n': 1}, 'b': {'n': 2}})) == [1, 2]

    def test_flatten(self):
        assert s('[]', [[1, 2], [3], 4]) == [1, 2, 3, 4]
        assert s('a[].b', {'a': [{'b': 1}, {'b': 2}]}) == [1, 2]

    def test_filter(self):
        data = {'c': [{'name': 'a', 'v': 1}, {'name': 'b', 'v': 2}]}
        assert s("c[?name=='a'].v", data) == [1]
        assert s('c[?v>`1`].name', data) == ['b']

    def test_multiselect(self):
        assert s('{x: a, y: b}', {'a': 1, 'b': 2}) == {'x': 1, 'y': 2}
        assert s('[a, b]', {'a': 1, 'b': 2}) == [1, 2]
        assert s('{x: a}', None) is None

    def test_pipe(self):
        assert s('a[*].n | [0]', {'a': [{'n': 5}]}) == 5

    def test_or_and_not(self):
        assert s('a || b', {'b': 2}) == 2
        assert s('a && b', {'a': 1, 'b': 2}) == 2
        assert s('!a', {'a': ''}) is True
        assert s('!a', {'a': 'x'}) is False

    def test_comparators(self):
        assert s('a == b', {'a': 1, 'b': 1}) is True
        assert s('a == b', {'a': True, 'b': 1}) is False  # bool != number
        assert s("a < b", {'a': 1, 'b': 2}) is True
        assert s("a < b", {'a': 'x', 'b': 'y'}) is None  # ordering only numbers

    def test_literal(self):
        assert s('`[1, 2]`', {}) == [1, 2]
        assert s("'raw'", {}) == 'raw'
        assert s('`"quoted"`', {}) == 'quoted'

    def test_current_and_root_expr(self):
        assert s('@', [1]) == [1]
        assert s('length(@)', [1, 2]) == 2

    def test_projection_stops_at_null(self):
        assert s('a[*].b.c', {'a': [{'b': None}]}) == []

    def test_nested_projections(self):
        data = {'a': [{'b': [{'c': 1}, {'c': 2}]}, {'b': [{'c': 3}]}]}
        assert s('a[*].b[*].c', data) == [[1, 2], [3]]
        assert s('a[].b[].c', data) == [1, 2, 3]


class TestBuiltins:
    def test_length_keys_values(self):
        assert s('length(a)', {'a': [1, 2]}) == 2
        assert s('keys(@)', {'b': 1, 'a': 2}) == ['b', 'a']
        assert s('values(@)', {'a': 1}) == [1]

    def test_sort_by_max_by(self):
        data = [{'v': 3}, {'v': 1}, {'v': 2}]
        assert s('sort_by(@, &v)[0].v', data) == 1
        assert s('max_by(@, &v).v', data) == 3
        assert s('min_by(@, &v).v', data) == 1

    def test_contains_starts_ends(self):
        assert s("contains(@, 'a')", ['a', 'b']) is True
        assert s("starts_with(@, 'ab')", 'abc') is True
        assert s("ends_with(@, 'bc')", 'abc') is True

    def test_to_number_to_string(self):
        assert s('to_number(@)', '42') == 42
        assert s('to_string(@)', 42) == '42'
        assert s('to_string(@)', {'a': 1}) == '{"a":1}'

    def test_map_join_merge(self):
        assert s('map(&n, @)', [{'n': 1}, {'n': 2}]) == [1, 2]
        assert s("join('-', @)", ['a', 'b']) == 'a-b'
        assert s('merge(@, `{"b": 2}`)', {'a': 1}) == {'a': 1, 'b': 2}

    def test_math(self):
        assert s('abs(`-3`)', {}) == 3
        assert s('ceil(`1.2`)', {}) == 2
        assert s('floor(`1.8`)', {}) == 1
        assert s('sum(@)', [1, 2, 3]) == 6
        assert s('avg(@)', [2, 4]) == 3
        assert s('max(@)', [1, 5, 2]) == 5
        assert s('min(@)', [1, 5, 2]) == 1

    def test_type_not_null_reverse(self):
        assert s('type(@)', 'x') == 'string'
        assert s('type(@)', True) == 'boolean'
        assert s('not_null(a, b)', {'b': 2}) == 2
        assert s('reverse(@)', [1, 2]) == [2, 1]
        assert s('reverse(@)', 'ab') == 'ba'

    def test_to_array(self):
        assert s('to_array(@)', 1) == [1]
        assert s('to_array(@)', [1]) == [1]


class TestKyvernoFunctions:
    def test_compare_equal_fold(self):
        assert s("compare('a', 'b')", {}) == -1
        assert s("compare('b', 'a')", {}) == 1
        assert s("compare('a', 'a')", {}) == 0
        assert s("equal_fold('Abc', 'abC')", {}) is True

    def test_string_ops(self):
        assert s("replace('ababab', 'ab', 'x', `2`)", {}) == 'xxab'
        assert s("replace_all('a-b-c', '-', '+')", {}) == 'a+b+c'
        assert s("to_upper('ab')", {}) == 'AB'
        assert s("to_lower('AB')", {}) == 'ab'
        assert s("trim('  x  ', ' ')", {}) == 'x'
        assert s("split('a,b,c', ',')", {}) == ['a', 'b', 'c']
        assert s("split('abc', '')", {}) == ['a', 'b', 'c']
        assert s("truncate('abcdef', `3`)", {}) == 'abc'

    def test_regex(self):
        assert s("regex_match('^app-', 'app-backend')", {}) is True
        assert s("regex_match('^app-', 'backend')", {}) is False
        assert s("regex_replace_all('(\\d+)', 'v12', 'n$1')", {}) == 'vn12'
        assert s("regex_replace_all_literal('\\d+', 'v12', 'N')", {}) == 'vN'
        assert s("pattern_match('nginx:*', 'nginx:1.2')", {}) is True

    def test_label_match(self):
        assert s('label_match(`{"app": "web"}`, `{"app": "web", "x": "1"}`)', {}) is True
        assert s('label_match(`{"app": "web"}`, `{"app": "api"}`)', {}) is False

    def test_arithmetic_scalars(self):
        assert s('add(`1`, `2`)', {}) == 3
        assert s('subtract(`5`, `2`)', {}) == 3
        assert s('multiply(`3`, `4`)', {}) == 12
        assert s('divide(`10`, `4`)', {}) == 2.5
        assert s('modulo(`10`, `3`)', {}) == 1
        assert s("modulo('1152921504606846977', '3')", {}) == '2'  # 2^60+1 mod 3, exact

    def test_arithmetic_quantities(self):
        assert s("add('128Mi', '128Mi')", {}) == '256Mi'
        assert s("subtract('1Gi', '512Mi')", {}) == '512Mi'
        assert s("multiply('100m', `3`)", {}) == '300m'
        assert s("divide('1Gi', '512Mi')", {}) == 2.0
        assert s("add('10', '5')", {}) == '15'

    def test_arithmetic_durations(self):
        # note: '30m' parses as a *quantity* (30 milli) like the reference's
        # quantity-first operand parsing, so use 's'/'h' suffixed durations
        assert s("add('1h', '30s')", {}) == '1h0m30s'
        assert s("divide('1h', '120s')", {}) == 30.0

    def test_arithmetic_quantity_duration_ambiguity(self):
        # reference quirk: '30m' is quantity, mixing with duration errors
        with pytest.raises(JMESPathError):
            s("add('1h', '30m')", {})

    def test_arithmetic_mixed_error(self):
        with pytest.raises(JMESPathError):
            s("add('1h', '1Gi')", {})

    def test_base64(self):
        assert s("base64_encode('hello')", {}) == 'aGVsbG8='
        assert s("base64_decode('aGVsbG8=')", {}) == 'hello'

    def test_path_canonicalize(self):
        assert s("path_canonicalize('/var//lib/./x')", {}) == '/var/lib/x'

    def test_semver(self):
        assert s("semver_compare('1.2.3', '>=1.0.0')", {}) is True
        assert s("semver_compare('0.9.0', '>=1.0.0')", {}) is False
        assert s("semver_compare('1.5.0', '>=1.0.0 <2.0.0')", {}) is True
        assert s("semver_compare('2.1.0', '<2.0.0 || >=2.1.0')", {}) is True
        assert s("semver_compare('1.2.5', '1.2.x')", {}) is True
        assert s("semver_compare('1.3.0', '1.2.x')", {}) is False
        assert s("semver_compare('1.2.3', '>= 1.0.0')", {}) is True  # space after op

    def test_parse_json_yaml(self):
        assert s("parse_json('{\"a\": 1}')", {}) == {'a': 1}
        assert s("parse_yaml('a: 1')", {}) == {'a': 1}

    def test_items_object_from_lists(self):
        assert s('items(@, \'k\', \'v\')', {'b': 2, 'a': 1}) == [
            {'k': 'a', 'v': 1}, {'k': 'b', 'v': 2}]
        assert s("object_from_lists(`[\"a\",\"b\"]`, `[1,2]`)", {}) == {'a': 1, 'b': 2}
        assert s("object_from_lists(`[\"a\",\"b\"]`, `[1]`)", {}) == {'a': 1, 'b': None}

    def test_random(self):
        out = s("random('[a-z]{8}')", {})
        assert len(out) == 8 and out.islower()
        out2 = s("random('[0-9a-f]{4}')", {})
        assert len(out2) == 4

    def test_time_functions(self):
        assert s("time_add('2023-01-01T00:00:00Z', '1h')", {}) == '2023-01-01T01:00:00Z'
        assert s("time_diff('2023-01-01T00:00:00Z', '2023-01-01T02:30:00Z')", {}) == '2h30m0s'
        assert s("time_before('2023-01-01T00:00:00Z', '2024-01-01T00:00:00Z')", {}) is True
        assert s("time_after('2023-01-01T00:00:00Z', '2024-01-01T00:00:00Z')", {}) is False
        assert s("time_between('2023-06-01T00:00:00Z', '2023-01-01T00:00:00Z', '2024-01-01T00:00:00Z')", {}) is True
        assert s("time_utc('2023-01-01T05:00:00+05:00')", {}) == '2023-01-01T00:00:00Z'
        assert s("time_to_cron('2023-02-02T15:04:00Z')", {}) == '4 15 2 2 4'
        assert s("time_truncate('2023-01-01T10:35:00Z', '1h')", {}) == '2023-01-01T10:00:00Z'

    def test_time_parse_layout(self):
        assert s("time_parse('2006-01-02', '2023-05-04')", {}) == '2023-05-04T00:00:00Z'

    def test_time_since(self):
        assert s("time_since('', '2023-01-01T00:00:00Z', '2023-01-01T03:00:00Z')", {}) == '3h0m0s'


class TestErrors:
    def test_unknown_function(self):
        with pytest.raises(JMESPathError):
            s('nope(@)', {})

    def test_arity(self):
        with pytest.raises(JMESPathError):
            s('length()', {})

    def test_syntax(self):
        with pytest.raises(JMESPathError):
            jp.compile('a.[')
        with pytest.raises(JMESPathError):
            jp.compile('a ==')

    def test_type_error(self):
        with pytest.raises(JMESPathError):
            s('length(@)', 42)

    def test_divide_by_zero(self):
        with pytest.raises(JMESPathError):
            s('divide(`1`, `0`)', {})
