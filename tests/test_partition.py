"""Partitioned policy-set compilation (``kyverno_tpu/partition/``):
plan stability + the churn differ, partitioned-scan bit-identity
against the monolithic oracle, live scanner hot-swap with breaker
migration, per-partition verdict-cache generations, and the ISSUE
acceptance: a second process editing 1 of ~100 policies recompiles
exactly the touched partition (everything else AOT-loads) with
bit-identical output."""

import copy
import json
import os
import subprocess
import sys

import pytest

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.partition import census
from kyverno_tpu.partition.plan import (ChurnDiff, PartitionError,
                                        build_plan, coupling_signature,
                                        diff_plans, env_partitions)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KINDS = ['Pod', 'ConfigMap', 'Service']


def policy_raw(i, message=None, kind=None, name=None):
    return {
        'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
        'metadata': {'name': name or f'require-l{i}', 'annotations': {
            'pod-policies.kyverno.io/autogen-controllers': 'none'}},
        'spec': {'validationFailureAction': 'audit', 'rules': [
            {'name': f'l{i}-label',
             'match': {'any': [{'resources': {
                 'kinds': [kind or KINDS[i % 3]]}}]},
             'validate': {'message': message or f'label l{i} required',
                          'pattern': {'metadata': {'labels': {
                              f'l{i}': '?*'}}}}},
        ]}}


def policies_of(n):
    return [Policy(policy_raw(i)) for i in range(n)]


def pod(name, labels):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'uid': f'uid-{name}', 'labels': labels},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


# ---------------------------------------------------------------------------
# plan + differ


class TestPlan:
    def test_env_partitions_parsing(self, monkeypatch):
        monkeypatch.delenv('KTPU_PARTITIONS', raising=False)
        assert env_partitions() == 0
        monkeypatch.setenv('KTPU_PARTITIONS', '8')
        assert env_partitions() == 8
        monkeypatch.setenv('KTPU_PARTITIONS', '-3')
        assert env_partitions() == 0
        monkeypatch.setenv('KTPU_PARTITIONS', 'nope')
        assert env_partitions() == 0

    def test_build_plan_rejects_zero(self):
        with pytest.raises(PartitionError):
            build_plan(policies_of(3), 0)

    def test_plan_is_deterministic(self):
        pols = policies_of(20)
        a = build_plan(pols, 4)
        b = build_plan([Policy(policy_raw(i)) for i in range(20)], 4)
        assert a.assignment == b.assignment
        assert [p.fingerprint for p in a.partitions] == \
            [p.fingerprint for p in b.partitions]
        # every policy lands in exactly one partition
        covered = sorted(i for part in a.partitions
                         for i in part.policy_indices)
        assert covered == list(range(20))

    def test_coupling_signature_tracks_vocabulary(self):
        a = Policy(policy_raw(0, kind='Pod'))
        b = Policy(policy_raw(0, kind='Pod', name='other'))
        c = Policy(policy_raw(0, kind='Service'))
        assert coupling_signature(a) == coupling_signature(b)
        assert coupling_signature(a) != coupling_signature(c)

    def test_edit_touches_exactly_one_partition(self):
        raws = [policy_raw(i) for i in range(30)]
        old = build_plan([Policy(r) for r in raws], 5)
        edited = copy.deepcopy(raws)
        edited[7]['spec']['rules'][0]['validate']['message'] = 'edited'
        new = build_plan([Policy(r) for r in edited], 5)
        diff = diff_plans(old, new)
        assert diff.touched == (new.assignment[7],)
        assert len(diff.touched) + len(diff.unchanged) == \
            len(new.partitions)

    def test_insert_leaves_other_buckets_unchanged(self):
        raws = [policy_raw(i) for i in range(30)]
        old = build_plan([Policy(r) for r in raws], 5)
        # prepend: every existing policy's GLOBAL index shifts, but
        # the fingerprints hash content in set order, so only the new
        # policy's bucket is touched
        grown = [policy_raw(99, name='zz-new')] + raws
        new_pols = [Policy(r) for r in grown]
        new = build_plan(new_pols, 5)
        diff = diff_plans(old, new)
        assert diff.touched == (new.assignment[0],)

    def test_delete_touches_only_its_bucket(self):
        raws = [policy_raw(i) for i in range(30)]
        pols = [Policy(r) for r in raws]
        old = build_plan(pols, 5)
        victim = 11
        shrunk = [p for i, p in enumerate(pols) if i != victim]
        new = build_plan(shrunk, 5)
        diff = diff_plans(old, new)
        assert diff.touched == (old.assignment[victim],)

    def test_first_build_touches_everything(self):
        plan = build_plan(policies_of(10), 3)
        diff = diff_plans(None, plan)
        assert diff.unchanged == ()
        assert sorted(diff.touched) == sorted(
            p.pid for p in plan.partitions)
        assert isinstance(diff, ChurnDiff)
        assert diff.to_dict()['unchanged'] == []


# ---------------------------------------------------------------------------
# partitioned scan = monolithic oracle, bit for bit


class TestPartitionedScan:
    def _statuses(self, policies, resources):
        from kyverno_tpu.compiler.scan import BatchScanner
        return BatchScanner(policies), \
            BatchScanner(policies).scan_statuses(resources)

    def test_bit_identity_vs_monolithic(self, monkeypatch):
        import numpy as np
        from kyverno_tpu.compiler.scan import BatchScanner
        pols = policies_of(12)
        resources = [pod(f'p{j}', {f'l{j % 12}': 'x'} if j % 2 else {})
                     for j in range(9)]
        monkeypatch.setenv('KTPU_PARTITIONS', '0')
        mono = BatchScanner(policies_of(12))
        assert mono._pset is None
        ms, md, mm = mono.scan_statuses(copy.deepcopy(resources))
        monkeypatch.setenv('KTPU_PARTITIONS', '4')
        census.reset()
        part = BatchScanner(pols)
        assert part._pset is not None and part._composer is not None
        # partitioned dispatches never ship whole-set admission lanes:
        # the host matcher decides rows (plan=None semantics)
        assert part._adm is None
        ps, pd, pm = part.scan_statuses(copy.deepcopy(resources))
        assert np.array_equal(ms, ps)
        assert np.array_equal(md, pd)
        assert np.array_equal(mm, pm)
        # the plan registered with the census under the set fingerprint
        rep = census.report()
        assert any(s['set_fingerprint'] == part.fingerprint
                   for s in rep['sets'])

    def test_census_report_shape(self, monkeypatch):
        monkeypatch.setenv('KTPU_PARTITIONS', '3')
        census.reset()
        plan = build_plan(policies_of(6), 3)
        census.record_plan('fp-x', plan, serial=7,
                           diff=diff_plans(None, plan))
        census.record_swap('validate', 1, 2, breaker_state='open',
                           touched=[0])
        rep = census.report()
        assert rep['sets'][0]['serial'] == 7
        assert rep['sets'][0]['last_diff']['unchanged'] == []
        assert rep['swaps'][-1]['breaker_state'] == 'open'
        assert rep['swaps'][-1]['touched_partitions'] == [0]
        census.reset()


# ---------------------------------------------------------------------------
# hot-swap under live traffic: breaker state migrates, never resets


class TestHotSwap:
    def test_install_scanner_swaps_and_migrates_breaker(self, monkeypatch):
        from types import SimpleNamespace
        from kyverno_tpu.observability import metrics as metrics_mod
        from kyverno_tpu.observability.metrics import MetricsRegistry
        from kyverno_tpu.policycache.cache import Cache
        from kyverno_tpu.serving import breaker as breaker_mod
        from kyverno_tpu.webhooks.handlers import ResourceHandlers
        reg = MetricsRegistry()
        monkeypatch.setattr(metrics_mod, '_GLOBAL', reg)
        census.reset()
        handlers = ResourceHandlers(Cache())
        pols_a = [Policy(policy_raw(i)) for i in range(3)]
        base_a = tuple(id(p) for p in pols_a)
        key_a = ('validate',) + base_a
        handlers._install_scanner(key_a, base_a, 'validate', pols_a,
                                  SimpleNamespace(serial=101, _pset=None))
        # trip the breaker on the predecessor's key
        for _ in range(50):
            state = handlers._breakers.record_failure(
                base_a, pols_a, 'backend fault')
            if state == breaker_mod.OPEN:
                break
        assert handlers._breakers.state(base_a) == breaker_mod.OPEN
        # churn: same logical set (same names), new Policy objects
        pols_b = [Policy(policy_raw(i, message='edited'))
                  for i in range(3)]
        base_b = tuple(id(p) for p in pols_b)
        key_b = ('validate',) + base_b
        handlers._install_scanner(key_b, base_b, 'validate', pols_b,
                                  SimpleNamespace(serial=102, _pset=None))
        assert key_a not in handlers._scanners
        assert key_b in handlers._scanners
        # the fault is NOT forgiven by the recompile...
        assert handlers._breakers.state(base_b) == breaker_mod.OPEN
        # ...and the retired key no longer holds it
        assert handlers._breakers.state(base_a) == breaker_mod.CLOSED
        assert reg.counter_value('kyverno_tpu_scanner_hot_swaps_total',
                                 kind='validate') == 1
        assert reg.counter_value(
            'kyverno_tpu_breaker_migrations_total') == 1
        swap = census.report()['swaps'][-1]
        assert (swap['old_serial'], swap['new_serial']) == (101, 102)
        assert swap['breaker_state'] == breaker_mod.OPEN
        census.reset()

    def test_unrelated_set_does_not_swap(self):
        from types import SimpleNamespace
        from kyverno_tpu.policycache.cache import Cache
        from kyverno_tpu.webhooks.handlers import ResourceHandlers
        handlers = ResourceHandlers(Cache())
        pols_a = [Policy(policy_raw(i)) for i in range(3)]
        pols_b = [Policy(policy_raw(i + 50)) for i in range(3)]
        for n, pols in ((1, pols_a), (2, pols_b)):
            base = tuple(id(p) for p in pols)
            handlers._install_scanner(
                ('validate',) + base, base, 'validate', pols,
                SimpleNamespace(serial=n, _pset=None))
        # zero name overlap: both scanners stay live
        assert len(handlers._scanners) == 2

    def test_migrate_without_entry_is_closed(self):
        from kyverno_tpu.serving import breaker as breaker_mod
        from kyverno_tpu.serving.breaker import BreakerRegistry
        reg = BreakerRegistry()
        assert reg.migrate(('old',), ('new',)) == breaker_mod.CLOSED


# ---------------------------------------------------------------------------
# per-partition verdict-cache generations


class TestPartitionedVerdictCache:
    def _cache(self, n_pols=8, n_parts=3):
        from kyverno_tpu.verdictcache.partitioned import \
            PartitionedVerdictCache
        pols = policies_of(n_pols)
        plan = build_plan(pols, n_parts)
        return PartitionedVerdictCache(plan, pols), plan, pols

    def _row(self, pols, indexes, result='pass'):
        return [{'policy': pols[i].get_kind_and_name(),
                 'rule': f'l{i}-label', 'result': result,
                 'scored': True} for i in indexes]

    def test_store_lookup_roundtrip(self):
        vc, plan, pols = self._cache()
        results = self._row(pols, range(8))
        vc.store('d1', 'uid-1', results,
                 {'pass': 8, 'fail': 0, 'warn': 0, 'error': 0,
                  'skip': 0}, list(range(8)))
        row = vc.lookup('d1')
        assert row is not None
        assert [r['policy'] for r in row['r']] == \
            sorted(r['policy'] for r in results)
        assert row['s']['pass'] == 8 and row['s']['fail'] == 0
        assert row['p'] == list(range(8))
        assert vc.stats()['hits'] == 1

    def test_lookup_requires_every_partition(self):
        vc, plan, pols = self._cache()
        # a row missing from even one generation must miss whole
        sub = next(iter(vc._parts.values()))
        vc.store('d2', 'u', self._row(pols, [0]),
                 {'pass': 1, 'fail': 0, 'warn': 0, 'error': 0,
                  'skip': 0}, [0])
        sub._rows.clear()
        assert vc.lookup('d2') is None
        assert vc.stats()['misses'] == 1

    def test_partial_and_merge_scoped(self):
        vc, plan, pols = self._cache()
        results = self._row(pols, range(8))
        vc.store('d3', 'uid-3', results,
                 {'pass': 8, 'fail': 0, 'warn': 0, 'error': 0,
                  'skip': 0}, list(range(8)))
        scoped_pid = plan.partitions[0].pid
        scoped_globals = list(plan.partitions[0].policy_indices)
        # evict the scoped partition's generation (the churn)
        vc._parts[scoped_pid]._rows.clear()
        assert vc.lookup('d3') is None
        cached = vc.partial('d3', frozenset([scoped_pid]))
        assert cached is not None and scoped_pid not in cached
        assert vc.stats()['partial_hits'] == 1
        # re-scan ONLY the scoped partition's members, fail this time
        rescan = self._row(pols, scoped_globals, result='fail')
        merged, summary, gidx = vc.merge_scoped(
            'd3', 'uid-3', cached, rescan, None, scoped_globals,
            ts=1754000000)
        assert gidx == list(range(8))
        assert summary['fail'] == len(scoped_globals)
        assert summary['pass'] == 8 - len(scoped_globals)
        assert [r['policy'] for r in merged] == \
            sorted(r['policy'] for r in results)
        # the digest is whole again: full lookup hits
        assert vc.lookup('d3') is not None

    def test_generation_carries_over_by_fingerprint(self):
        from kyverno_tpu.verdictcache.partitioned import \
            PartitionedVerdictCache
        vc, plan, pols = self._cache()
        vc.store('d4', 'u4', self._row(pols, range(8)),
                 {'pass': 8, 'fail': 0, 'warn': 0, 'error': 0,
                  'skip': 0}, list(range(8)))
        raws = [policy_raw(i) for i in range(8)]
        edited = plan.partitions[0].policy_indices[0]
        raws[edited]['spec']['rules'][0]['validate']['message'] = 'x'
        pols2 = [Policy(r) for r in raws]
        plan2 = build_plan(pols2, 3)
        vc2 = PartitionedVerdictCache(plan2, pols2, prev=vc)
        touched = diff_plans(plan, plan2).touched
        for part in plan2.partitions:
            sub = vc2._parts[part.pid]
            if part.pid in touched:
                assert len(sub) == 0  # fresh generation
            else:
                assert sub is vc._parts[part.pid]  # adopted in place


# ---------------------------------------------------------------------------
# controller flow: dense scan -> replay -> churn -> scoped rescan -> replay


class TestControllerChurn:
    NOW = 1754000000.0

    def _controller(self, policies):
        from kyverno_tpu.dclient.client import FakeClient
        from kyverno_tpu.reports.controllers import (
            BackgroundScanController, MetadataCache)
        ctrl = BackgroundScanController(FakeClient(), policies,
                                        cache=MetadataCache())
        return ctrl

    def _reports(self, ctrl):
        out = []
        for r in sorted(ctrl.client.list_resource(
                'kyverno.io/v1alpha2', 'BackgroundScanReport', 'default',
                None), key=lambda r: r['metadata']['name']):
            meta = {k: v for k, v in r['metadata'].items()
                    if k not in ('resourceVersion', 'uid')}
            out.append(dict(r, metadata=meta))
        return out

    def test_churn_scoped_rescan_and_bit_identity(self, monkeypatch,
                                                  tmp_path):
        monkeypatch.setenv('KTPU_VERDICT_CACHE', '1')
        monkeypatch.setenv('KTPU_VERDICT_CACHE_DIR',
                           str(tmp_path / 'vc'))
        monkeypatch.setenv('KTPU_PARTITIONS', '4')
        raws = [policy_raw(i) for i in range(12)]
        pods = [pod(f'p{j}', {f'l{j % 12}': 'x'}) for j in range(20)]
        ctrl = self._controller([Policy(r) for r in raws])
        for p in pods:
            ctrl.enqueue(p)
        ctrl.reconcile(now=self.NOW)
        assert ctrl.rescan_stats['rows_scanned'] == 20
        # warm replay: zero scans
        ctrl.reset_scan_state()
        ctrl.enqueue_all()
        ctrl.reconcile(now=self.NOW + 60)
        assert ctrl.rescan_stats['rows_replayed'] == 20
        # churn: edit one policy -> scoped pids = its partition only
        raws2 = copy.deepcopy(raws)
        raws2[5]['spec']['rules'][0]['validate']['message'] = 'edited'
        ctrl.set_policies([Policy(r) for r in raws2])
        assert ctrl._scoped_pids is not None
        assert len(ctrl._scoped_pids) < ctrl._partition_plan.n_parts
        ctrl.enqueue_all()
        ctrl.reconcile(now=self.NOW + 120)
        # every row re-scanned ONLY against the touched partitions
        assert ctrl.rescan_stats['rows_scoped'] == 20
        # scoped fills completed the generations: full replay again
        ctrl.reset_scan_state()
        ctrl.enqueue_all()
        ctrl.reconcile(now=self.NOW + 180)
        assert ctrl.rescan_stats['rows_replayed'] == 20
        # oracle: monolithic scan, cache off, same final policy set
        monkeypatch.setenv('KTPU_PARTITIONS', '0')
        monkeypatch.setenv('KTPU_VERDICT_CACHE', '0')
        oracle = self._controller([Policy(r) for r in raws2])
        for p in pods:
            oracle.enqueue(p)
        oracle.reconcile(now=self.NOW + 180)
        assert self._reports(ctrl) == self._reports(oracle)

    def test_second_process_generations_replay(self, monkeypatch,
                                               tmp_path):
        monkeypatch.setenv('KTPU_VERDICT_CACHE', '1')
        monkeypatch.setenv('KTPU_VERDICT_CACHE_DIR',
                           str(tmp_path / 'vc'))
        monkeypatch.setenv('KTPU_PARTITIONS', '3')
        raws = [policy_raw(i) for i in range(9)]
        pods = [pod(f'p{j}', {f'l{j % 9}': 'x'}) for j in range(10)]
        ctrl = self._controller([Policy(r) for r in raws])
        for p in pods:
            ctrl.enqueue(p)
        ctrl.reconcile(now=self.NOW)
        ctrl.verdict_cache.flush()
        # a fresh controller (second process): the per-partition
        # snapshots on disk warm every row
        ctrl2 = self._controller([Policy(r) for r in raws])
        for p in pods:
            ctrl2.enqueue(p)
        ctrl2.reconcile(now=self.NOW + 60)
        assert ctrl2.rescan_stats['rows_replayed'] == 10


# ---------------------------------------------------------------------------
# ISSUE acceptance: second-process incremental warm.  Fresh interpreters
# (cold jit caches, no forced 8-device mesh so the AOT store is live):
# process 1 compiles + persists every partition executable; process 2
# serves entirely from aot_load; process 3 edits 1 of 100 policies and
# recompiles EXACTLY the touched partition, with bit-identical verdict
# matrices throughout.

_WARM_SCRIPT = r'''
import json, os, sys
from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import device as devtel
from kyverno_tpu.observability.metrics import MetricsRegistry

N = 100


def policy(i, message=None):
    return {
        'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
        'metadata': {'name': f'require-l{i}', 'annotations': {
            'pod-policies.kyverno.io/autogen-controllers': 'none'}},
        'spec': {'validationFailureAction': 'audit', 'rules': [
            {'name': f'l{i}',
             'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
             'validate': {'message': message or f'label l{i} required',
                          'pattern': {'metadata': {'labels': {
                              f'l{i}': '?*'}}}}},
        ]}}


raws = [policy(i) for i in range(N)]
churn = os.environ.get('KTPU_TEST_CHURN_INDEX')
if churn is not None:
    k = int(churn)
    raws[k] = policy(k, message=f'label l{k} required [edited]')
policies = [Policy(r) for r in raws]

from kyverno_tpu.partition.plan import build_plan, diff_plans
n_parts = int(os.environ['KTPU_PARTITIONS'])
orig = build_plan([Policy(policy(i)) for i in range(N)], n_parts)
diff = diff_plans(orig, build_plan(policies, n_parts))


def pod(i):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'p{i}', 'namespace': 'default',
                         'labels': {f'l{i}': 'x'} if i % 2 else {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx:1'}]}}


reg = devtel.configure(MetricsRegistry())
from kyverno_tpu.compiler.scan import BatchScanner
scanner = BatchScanner(policies)
status, detail, match = scanner.scan_statuses([pod(i) for i in range(4)])
from kyverno_tpu.compiler import aot
aot.flush_stores()
C = 'kyverno_tpu_compile_cache_requests_total'
print(json.dumps({
    'n_partitions': len(scanner._pset.runtimes),
    'touched': sorted(diff.touched),
    'miss': reg.counter_value(C, result='miss'),
    'aot_load': reg.counter_value(C, result='aot_load'),
    'aot_store': reg.counter_value(C, result='aot_store'),
    'status': status.tolist(),
    'detail': detail.tolist(),
    'match': match.tolist(),
}))
'''


def _run_partitioned_process(cache_dir, churn_index=None, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env.update({
        'JAX_PLATFORMS': 'cpu',
        'PYTHONPATH': REPO,
        'KTPU_AOT': '1',
        'KTPU_AOT_CACHE_DIR': os.path.join(str(cache_dir), 'aot'),
        'KTPU_COMPILE_CACHE': os.path.join(str(cache_dir), 'xla'),
        'KTPU_PARTITIONS': '5',
    })
    if churn_index is not None:
        env['KTPU_TEST_CHURN_INDEX'] = str(churn_index)
    out = subprocess.run([sys.executable, '-c', _WARM_SCRIPT],
                         env=env, cwd=REPO, capture_output=True,
                         text=True, timeout=timeout)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_incremental_warm_recompiles_only_touched_partition(tmp_path):
    first = _run_partitioned_process(tmp_path)
    assert first['touched'] == []
    assert first['miss'] == first['n_partitions']
    assert first['aot_store'] == first['n_partitions']
    assert first['aot_load'] == 0

    second = _run_partitioned_process(tmp_path)
    assert second['miss'] == 0
    assert second['aot_load'] == second['n_partitions']

    churn = _run_partitioned_process(tmp_path, churn_index=17)
    # a single-policy edit touches exactly one bucket...
    assert len(churn['touched']) == 1
    # ...which is the ONLY fresh compile; the rest warm-load
    assert churn['miss'] == 1
    assert churn['aot_load'] == churn['n_partitions'] - 1
    assert churn['aot_store'] == 1

    # the edit changed a message, not a pattern: verdict matrices are
    # bit-identical across all three processes
    for field in ('status', 'detail', 'match'):
        assert first[field] == second[field] == churn[field], field
