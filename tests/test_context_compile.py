"""Context-entry rules compile to device (VERDICT r4 #3): ConfigMap/
apiCall context entries whose values feed no compiled lane run on the
device path, with the host engine's load-failure semantics enforced per
resource (reference: pkg/engine/jsonContext.go:126,304)."""

import random

import pytest

from kyverno_tpu.api.policy import load_policies_from_yaml
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.apicall import make_context_loader
from kyverno_tpu.engine.engine import Engine

CTX_PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: cm-context
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: needs-team-cm
      match: {any: [{resources: {kinds: [Pod]}}]}
      context:
        - name: teamcfg
          configMap:
            name: team-config
            namespace: "{{request.object.metadata.namespace}}"
      validate:
        message: "image tag required"
        pattern:
          spec:
            containers:
              - image: "*:*"
"""


def pod(name, ns, image='nginx:1.25'):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': ns},
            'spec': {'containers': [{'name': 'c', 'image': image}]}}


def test_pack_fully_compiles():
    import bench
    cps = compile_policies(bench.load_policy_pack())
    assert len(cps.host_rules) == 0
    assert len(cps.programs) == 92
    assert any(p.context_spec for p in cps.programs
               if 'select-secrets' in p.rule_name)


def test_value_feeding_context_stays_host():
    # a rule whose validate references the entry name must stay host
    pack = CTX_PACK.replace('image tag required',
                            'team is {{teamcfg.data.team}}')
    cps = compile_policies(load_policies_from_yaml(pack))
    assert len(cps.host_rules) == 1
    assert len(cps.programs) == 0


def test_device_matches_host_across_load_outcomes():
    client = FakeClient()
    client.create_resource('v1', 'Namespace', '', {
        'apiVersion': 'v1', 'kind': 'Namespace',
        'metadata': {'name': 'has-cm'}})
    client.create_resource('v1', 'ConfigMap', 'has-cm', {
        'apiVersion': 'v1', 'kind': 'ConfigMap',
        'metadata': {'name': 'team-config', 'namespace': 'has-cm'},
        'data': {'team': 'a'}})
    policies = load_policies_from_yaml(CTX_PACK)
    engine = Engine(context_loader=make_context_loader(dclient=client))
    scanner = BatchScanner(policies, engine=engine)
    assert not scanner.cps.host_rules

    pods = [pod('ok', 'has-cm'),            # cm exists, pattern passes
            pod('bad', 'has-cm', 'nginx'),  # cm exists, pattern fails
            pod('nocm', 'missing-ns')]      # cm load fails -> host error
    out = scanner.scan(pods)
    for doc, responses in zip(pods, out):
        host = engine.apply_background_checks(
            PolicyContext(policies[0], new_resource=doc))
        got = {r.name: (r.status, r.message)
               for resp in responses for r in resp.policy_response.rules}
        want = {r.name: (r.status, r.message)
                for r in host.policy_response.rules}
        assert got == want, doc['metadata']['name']
    # sanity: the three outcomes genuinely differ
    statuses = [resp.policy_response.rules[0].status
                for responses in out for resp in responses
                if resp.policy_response.rules]
    assert 'pass' in statuses and 'fail' in statuses
    assert len(set(statuses)) >= 2
