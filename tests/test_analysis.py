"""ktpu-lint framework tests: one positive and one negative fixture
per rule id (deleting a rule's implementation fails its fixture test),
plus suppression semantics and baseline round-trips."""

import json
import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from kyverno_tpu.analysis import Analyzer, RULES, write_baseline  # noqa: E402
from kyverno_tpu.analysis.knobs import KNOBS  # noqa: E402
from kyverno_tpu.observability.catalog import METRICS  # noqa: E402
from kyverno_tpu.observability.coverage import REASONS  # noqa: E402


def run(tmp_path, sources, rules=None, baseline=None):
    """Write {relpath: source} under tmp_path and analyze it."""
    for rel, src in sources.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    a = Analyzer([str(tmp_path)], str(tmp_path),
                 baseline_path=baseline, rules=rules)
    return a.run()


def rule_ids(report):
    return {f.rule_id for f in report.active}


JIT_PRELUDE = """\
    import jax
    import jax.numpy as jnp
"""


# -- KTPU1xx: trace safety ---------------------------------------------------

def test_ktpu101_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        x = jnp.sum(t)
        return x.item()
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert rule_ids(rep) == {'KTPU101'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        return jnp.sum(t)
    jf = jax.jit(f)

    def host_only(t):
        return t.item()
    """}, rules=['KTPU101'])
    assert not rep.active  # .item() outside the jit graph is fine


def test_ktpu101_transitive_reachability(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def helper(t):
        return t.tolist()

    def f(t):
        return helper(t)
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert rule_ids(rep) == {'KTPU101'}


def test_ktpu102_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        return int(jnp.sum(t))
    jf = jax.jit(f)
    """}, rules=['KTPU102'])
    assert rule_ids(rep) == {'KTPU102'}
    # a *static* jit arg is a plain python value — casting it is fine;
    # without static_argnames the param is a tracer and the cast is a
    # finding (see test_taint_entry_param below)
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t, n):
        return t * int(n)
    jf = jax.jit(f, static_argnames='n')
    """}, rules=['KTPU102'])
    assert not rep.active  # cast of a static python value


def test_ktpu103_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        y = jnp.sum(t)
        if y > 0:
            return t
        return -t
    jf = jax.jit(f)
    """}, rules=['KTPU103'])
    assert rule_ids(rep) == {'KTPU103'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t, mask):
        if mask is None:
            return t
        return jnp.where(mask, t, 0)
    jf = jax.jit(f)
    """}, rules=['KTPU103'])
    assert not rep.active  # `is None` gates optionality, not tracers


# -- KTPU2xx: retrace hazards ------------------------------------------------

def test_ktpu201_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    STATE = {}

    def f(t):
        return t + len(STATE)
    jf = jax.jit(f)
    """}, rules=['KTPU201'])
    assert rule_ids(rep) == {'KTPU201'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    STATE = (1, 2)

    def f(t):
        return t + len(STATE)
    jf = jax.jit(f)
    """}, rules=['KTPU201'])
    assert not rep.active  # tuples cannot drift under the executable


def test_ktpu201_enclosing_scope(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def build():
        holder = {'k': None}

        def f(t):
            return t + len(holder)
        return jax.jit(f)
    """}, rules=['KTPU201'])
    assert rule_ids(rep) == {'KTPU201'}


def test_ktpu202_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def g(x, cfg=[1]):
        return x
    jg = jax.jit(g, static_argnums=1)
    """}, rules=['KTPU202'])
    assert rule_ids(rep) == {'KTPU202'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def g(x, cfg=(1,)):
        return x
    jg = jax.jit(g, static_argnums=1)
    """}, rules=['KTPU202'])
    assert not rep.active


def test_ktpu203_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        if t.ndim == 1:
            return t[:, None]
        return t
    jf = jax.jit(f)
    """}, rules=['KTPU203'])
    assert rule_ids(rep) == {'KTPU203'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        return jnp.expand_dims(t, -1)
    jf = jax.jit(f)
    """}, rules=['KTPU203'])
    assert not rep.active


def test_ktpu204_positive_negative(tmp_path):
    # the retired power-of-two bucket ladder regrowing: flagged
    rep = run(tmp_path, {'a.py': """\
    from .encode import encode_batch

    def work(docs, cps, n):
        bucket = max(64, 1 << (n - 1).bit_length())
        return encode_batch(docs, cps, padded_n=bucket)
    """}, rules=['KTPU204'])
    assert rule_ids(rep) == {'KTPU204'}
    # a hard-coded row count is a shape too
    rep = run(tmp_path, {'a.py': """\
    from .encode import encode_mutate_batch

    def work(docs, program):
        return encode_mutate_batch(docs, program, padded_n=4096)
    """}, rules=['KTPU204'])
    assert rule_ids(rep) == {'KTPU204'}
    # canonical-table provenance: clean
    rep = run(tmp_path, {'a.py': """\
    from .encode import encode_batch
    from .shapes import canonical_capacity

    def work(docs, cps, n):
        bucket = canonical_capacity(n)
        return encode_batch(docs, cps, padded_n=bucket)
    """}, rules=['KTPU204'])
    assert not rep.active
    # unpadded (padded_n absent / 0) encodes are not shape decisions
    rep = run(tmp_path, {'a.py': """\
    from .encode import encode_batch

    def work(docs, cps):
        return encode_batch(docs, cps, padded_n=0)
    """}, rules=['KTPU204'])
    assert not rep.active


def test_ktpu205_positive_negative(tmp_path):
    # per-row context dicts in the encode entry itself: flagged
    rep = run(tmp_path, {'a.py': """\
    def encode_batch(docs, cps):
        bases = [{'request': {'object': d}} for d in docs]
        return bases
    """}, rules=['KTPU205'])
    assert rule_ids(rep) == {'KTPU205'}
    # one-level callee on the hot path: flagged (dict() and deepcopy
    # and json.dumps all count)
    rep = run(tmp_path, {'a.py': """\
    import copy
    import json

    def _ctx_rows(docs):
        out = []
        for d in docs:
            out.append(copy.deepcopy(d))
            out.append(json.dumps(d))
        return out

    def encode_mutate_batch(docs, program, padded_n=0):
        return _ctx_rows(docs)
    """}, rules=['KTPU205'])
    assert rule_ids(rep) == {'KTPU205'}
    assert len(rep.active) == 2
    # allocation hoisted out of the loop: clean
    rep = run(tmp_path, {'a.py': """\
    def encode_batch(docs, cps):
        shared = {'request': {'object': None}}
        out = []
        for d in docs:
            shared['request']['object'] = d
            out.append(len(shared))
        return out
    """}, rules=['KTPU205'])
    assert not rep.active
    # dict-in-loop in a function NOT reachable from an encode entry
    rep = run(tmp_path, {'a.py': """\
    def encode_batch(docs, cps):
        return len(docs)

    def unrelated(docs):
        return [{'k': d} for d in docs]
    """}, rules=['KTPU205'])
    assert not rep.active
    # two-level call chains are out of scope (one-level resolution,
    # like KTPU204)
    rep = run(tmp_path, {'a.py': """\
    def _deep(docs):
        return [{'k': d} for d in docs]

    def _mid(docs):
        return _deep(docs)

    def encode_batch(docs, cps):
        return _mid(docs)
    """}, rules=['KTPU205'])
    assert not rep.active
    # suppression with a reason works like every other rule
    rep = run(tmp_path, {'a.py': """\
    def encode_batch(docs, cps):
        # ktpu: noqa[KTPU205] -- test fixture: deliberate per-row dict
        return [{'request': {'object': d}} for d in docs]
    """}, rules=['KTPU205'])
    assert not rep.active
    assert len(rep.suppressed) == 1


# -- KTPU3xx: fallback taxonomy ----------------------------------------------

def test_ktpu301_positive_negative(tmp_path):
    rep = run(tmp_path, {'compiler/c.py': """\
    from ..compiler.ir import CompileError

    def compile_rule(rule):
        raise CompileError('nope', reason='not_a_real_reason')
    """}, rules=['KTPU301'])
    assert rule_ids(rep) == {'KTPU301'}
    rep = run(tmp_path, {'compiler/c.py': """\
    from ..compiler.ir import CompileError

    def compile_rule(rule):
        raise CompileError('nope', reason='host_closure')
    """}, rules=['KTPU301'])
    assert not rep.active


def test_ktpu302_positive_negative(tmp_path):
    rep = run(tmp_path, {'compiler/c.py': """\
    FALLBACK = object()

    def bad(doc):
        if not isinstance(doc, dict):
            return FALLBACK
        return doc
    """}, rules=['KTPU302'])
    assert rule_ids(rep) == {'KTPU302'}
    rep = run(tmp_path, {'compiler/c.py': """\
    FALLBACK = object()

    def good(doc, record_fallback):
        if not isinstance(doc, dict):
            record_fallback('mutate', 'non_dict_intermediate')
            return FALLBACK
        return doc
    """}, rules=['KTPU302'])
    assert not rep.active


def test_ktpu302_scoped_to_compiler(tmp_path):
    rep = run(tmp_path, {'engine/c.py': """\
    FALLBACK = object()

    def bad(doc):
        return FALLBACK
    """}, rules=['KTPU302'])
    assert not rep.active


def test_ktpu302_covers_device_mutate_package(tmp_path):
    """The device-side mutate package shares the FALLBACK discipline;
    engine/mutate/ (the host oracle) stays out of scope."""
    pos = tmp_path / 'pos'
    pos.mkdir()
    rep = run(pos, {'mutate/m.py': """\
    FALLBACK = object()

    def bad(doc):
        return FALLBACK
    """}, rules=['KTPU302'])
    assert rule_ids(rep) == {'KTPU302'}
    neg = tmp_path / 'neg'
    neg.mkdir()
    rep = run(neg, {'engine/mutate/m.py': """\
    FALLBACK = object()

    def bad(doc):
        return FALLBACK
    """}, rules=['KTPU302'])
    assert not rep.active


def test_ktpu303_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': 'X = 1\n'}, rules=['KTPU303'])
    # no reference site anywhere → every taxonomy reason is dead
    assert rule_ids(rep) == {'KTPU303'}
    assert len(rep.active) == len(REASONS)
    refs = ''.join(
        f"    raise CompileError('x', reason='{slug}')\n"
        for slug in sorted(REASONS))
    rep = run(tmp_path, {'a.py': 'def f():\n' + refs},
              rules=['KTPU303'])
    assert not rep.active


def test_ktpu304_positive_negative(tmp_path):
    # a serving-path handler that swallows Exception without shedding
    # or re-raising hides a degradation from every ledger
    rep = run(tmp_path, {'serving/a.py': """\
    def f():
        try:
            g()
        except Exception:
            return None
    """}, rules=['KTPU304'])
    assert rule_ids(rep) == {'KTPU304'}
    # recording a shed reason, re-raising, or narrowing the class —
    # and any handler OUTSIDE serving/ or pipeline.py — are all fine
    rep = run(tmp_path, {'serving/a.py': """\
    def f(ledger):
        try:
            g()
        except Exception:
            ledger.record_shed('scan_error')
        try:
            g()
        except Exception:
            raise
        try:
            g()
        except ValueError:
            return None
    """, 'elsewhere/a.py': """\
    def f():
        try:
            g()
        except Exception:
            return None
    """}, rules=['KTPU304'])
    assert not rep.active
    # pipeline.py is in scope wherever it lives
    rep = run(tmp_path, {'compiler/pipeline.py': """\
    def f():
        try:
            g()
        except BaseException:
            pass
    """}, rules=['KTPU304'])
    assert rule_ids(rep) == {'KTPU304'}


# -- KTPU4xx: env-knob registry ----------------------------------------------

def test_ktpu401_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    import os
    V = os.environ.get('KTPU_NOT_A_KNOB', '1')
    """}, rules=['KTPU401'])
    assert rule_ids(rep) == {'KTPU401'}
    rep = run(tmp_path, {'a.py': """\
    import os
    V = os.environ.get('KTPU_WARM', '1')
    W = __import__('os').environ.get('KTPU_SCAN_CHUNK', '16384')
    """}, rules=['KTPU401'])
    assert not rep.active


def test_ktpu402_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': 'X = 1\n'}, rules=['KTPU402'])
    assert rule_ids(rep) == {'KTPU402'}
    assert len(rep.active) == len(KNOBS)
    reads = 'import os\n' + ''.join(
        f"V{i} = os.environ.get('{name}')\n"
        for i, name in enumerate(sorted(KNOBS)))
    rep = run(tmp_path, {'a.py': reads}, rules=['KTPU402'])
    assert not rep.active


# -- KTPU5xx: metric catalog -------------------------------------------------

def test_ktpu501_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    def emit(reg):
        reg.inc('kyverno_tpu_not_in_catalog_total')
    """}, rules=['KTPU501'])
    assert rule_ids(rep) == {'KTPU501'}
    rep = run(tmp_path, {'a.py': """\
    def emit(reg):
        reg.inc('kyverno_tpu_host_fallback_total')
    """}, rules=['KTPU501'])
    assert not rep.active


def test_ktpu502_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, name):
        reg.inc(name)
    """}, rules=['KTPU502'])
    assert rule_ids(rep) == {'KTPU502'}
    rep = run(tmp_path, {'a.py': """\
    METRIC = 'kyverno_tpu_host_fallback_total'

    def emit(reg):
        reg.inc(METRIC)
    """}, rules=['KTPU502'])
    assert not rep.active


def test_ktpu503_positive_negative(tmp_path):
    from kyverno_tpu.analysis.catalog_pass import DEAD_METRIC_ALLOWLIST
    rep = run(tmp_path, {'a.py': 'X = 1\n'}, rules=['KTPU503'])
    assert rule_ids(rep) == {'KTPU503'}
    # a write site for every non-allowlisted metric is the clean state
    # (an allowlisted metric with a write site is a *stale* allowlist
    # entry — covered below)
    writes = 'def emit(reg):\n' + ''.join(
        f"    reg.inc('{name}')\n" for name in sorted(METRICS)
        if name not in DEAD_METRIC_ALLOWLIST)
    rep = run(tmp_path, {'a.py': writes}, rules=['KTPU503'])
    assert not rep.active


def test_ktpu503_stale_allowlist_entry(tmp_path):
    """An allowlist entry whose metric gained a write site is itself a
    finding — the allowlist stays minimal by construction, and newly
    landed subsystems can't hide behind it."""
    from kyverno_tpu.analysis.catalog_pass import DEAD_METRIC_ALLOWLIST
    allowlisted = sorted(DEAD_METRIC_ALLOWLIST)[0]
    writes = 'def emit(reg):\n' + ''.join(
        f"    reg.inc('{name}')\n" for name in sorted(METRICS))
    rep = run(tmp_path, {'a.py': writes}, rules=['KTPU503'])
    assert rule_ids(rep) == {'KTPU503'}
    assert any(allowlisted in f.message and 'stale' in f.message
               for f in rep.active)


def test_ktpu506_ms_into_seconds_metric(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, elapsed_ms):
        reg.observe('kyverno_tpu_scan_duration_seconds', elapsed_ms)
    """}, rules=['KTPU506'])
    assert rule_ids(rep) == {'KTPU506'}
    assert any('elapsed_ms' in f.message for f in rep.active)
    # a /1000 conversion anywhere in the expression is the fix
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, elapsed_ms):
        reg.observe('kyverno_tpu_scan_duration_seconds',
                    elapsed_ms / 1000.0)
    """}, rules=['KTPU506'])
    assert not rep.active
    # ... as is * 0.001
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, elapsed_ms):
        reg.observe('kyverno_tpu_scan_duration_seconds',
                    elapsed_ms * 0.001)
    """}, rules=['KTPU506'])
    assert not rep.active


def test_ktpu506_one_level_binding_resolution(tmp_path):
    # the ms value hides behind one local assignment (KTPU204 depth)
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, lat_ms):
        value = lat_ms
        reg.observe('kyverno_tpu_scan_duration_seconds', value)
    """}, rules=['KTPU506'])
    assert rule_ids(rep) == {'KTPU506'}
    # the binding carries the conversion: clean
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, lat_ms):
        value = lat_ms / 1000
        reg.observe('kyverno_tpu_scan_duration_seconds', value)
    """}, rules=['KTPU506'])
    assert not rep.active
    # a metric name flowing through a module constant still resolves
    rep = run(tmp_path, {'a.py': """\
    METRIC = 'kyverno_tpu_scan_duration_seconds'

    def emit(reg, lat_ms):
        reg.observe(METRIC, lat_ms)
    """}, rules=['KTPU506'])
    assert rule_ids(rep) == {'KTPU506'}


def test_ktpu506_len_of_str_into_bytes_metric(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    def emit(reg):
        body = 'x'.join(['a', 'b'])
        reg.inc('kyverno_tpu_response_bytes_total', len(body))
    """}, rules=['KTPU506'])
    assert rule_ids(rep) == {'KTPU506'}
    assert any('characters' in f.message for f in rep.active)
    # len of the encoded payload measures the wire size: clean
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, body):
        reg.inc('kyverno_tpu_response_bytes_total',
                len(body.encode()))
    """}, rules=['KTPU506'])
    assert not rep.active
    # an unresolvable bare name is not assumed to be a str
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, payload):
        reg.inc('kyverno_tpu_response_bytes_total', len(payload))
    """}, rules=['KTPU506'])
    assert not rep.active


def test_ktpu506_ignores_unitless_metrics_and_bucket_args(tmp_path):
    # no unit suffix — nothing to check
    rep = run(tmp_path, {'a.py': """\
    def emit(reg, lat_ms):
        reg.set_gauge('kyverno_tpu_admission_queue_depth', lat_ms)
    """}, rules=['KTPU506'])
    assert not rep.active
    # register_histogram's second arg is buckets, not a measurement
    rep = run(tmp_path, {'a.py': """\
    def setup(reg, buckets_ms):
        reg.register_histogram(
            'kyverno_tpu_scan_duration_seconds', buckets_ms)
    """}, rules=['KTPU506'])
    assert not rep.active


# -- KTPU504/505: span catalog -----------------------------------------------

def test_ktpu504_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    def f(tracing):
        with tracing.start_span('kyverno/not/cataloged'):
            pass
    """}, rules=['KTPU504'])
    assert rule_ids(rep) == {'KTPU504'}
    rep = run(tmp_path, {'a.py': """\
    def f(tracing):
        with tracing.start_span('kyverno/rescan'):
            pass
    """}, rules=['KTPU504'])
    assert not rep.active


def test_ktpu504_dynamic_and_stage_sites(tmp_path):
    # a route-templated f-string name is checked by literal prefix
    rep = run(tmp_path, {'a.py': """\
    def f(tracing, path):
        with tracing.start_span(f'webhooks{path}'):
            pass
    """}, rules=['KTPU504'])
    assert not rep.active
    # device stage timers map to kyverno/device/<stage>
    rep = run(tmp_path, {'a.py': """\
    def f(devtel):
        with devtel.stage('encode'):
            pass
    """}, rules=['KTPU504'])
    assert not rep.active
    rep = run(tmp_path, {'a.py': """\
    def f(devtel):
        with devtel.stage('not_a_stage'):
            pass
    """}, rules=['KTPU504'])
    assert rule_ids(rep) == {'KTPU504'}
    # a name flowing through a variable is uncheckable
    rep = run(tmp_path, {'a.py': """\
    def f(tracing, name):
        with tracing.start_span(name):
            pass
    """}, rules=['KTPU504'])
    assert rule_ids(rep) == {'KTPU504'}


def test_ktpu505_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': 'X = 1\n'}, rules=['KTPU505'])
    assert rule_ids(rep) == {'KTPU505'}
    # one dynamic site per prefix family marks the whole catalog used
    rep = run(tmp_path, {'a.py': """\
    def f(tracing, x):
        with tracing.start_span(f'kyverno/{x}'):
            pass
        with tracing.start_span(f'webhooks{x}'):
            pass
    """}, rules=['KTPU505'])
    assert not rep.active


def _stage_registry_uses():
    """One ``stage('<s>')`` site per registered pipeline stage — the
    clean-state floor for KTPU507 fixtures (mirrors how the KTPU503
    negative writes every cataloged metric)."""
    from kyverno_tpu.analysis.catalog_pass import load_stage_registry
    return 'def _uses(devtel):\n' + ''.join(
        f"    devtel.stage('{name}')\n"
        for name in sorted(load_stage_registry()))


def test_ktpu507_unregistered_stage_in_compiler(tmp_path):
    rep = run(tmp_path, {
        'compiler/c.py': """\
        def f(devtel):
            with devtel.stage('warp'):
                pass
        """,
        'u.py': _stage_registry_uses(),
    }, rules=['KTPU507'])
    assert rule_ids(rep) == {'KTPU507'}
    assert any("'warp'" in f.message for f in rep.active)
    # the same label registered (plus a use per registry entry) is clean
    rep = run(tmp_path, {'compiler/c.py': _stage_registry_uses()},
              rules=['KTPU507'])
    assert not rep.active


def test_ktpu507_outside_compiler_is_not_flagged(tmp_path):
    # engine-side stage timers are not pipeline stages — the
    # unregistered check is scoped to compiler/; the registry floor
    # still applies tree-wide
    rep = run(tmp_path, {
        'engine/e.py': """\
        def f(devtel):
            with devtel.stage('warp'):
                pass
        """,
        'u.py': _stage_registry_uses(),
    }, rules=['KTPU507'])
    assert not rep.active


def test_ktpu507_chunk_pipeline_stage_list(tmp_path):
    rep = run(tmp_path, {
        'compiler/c.py': """\
        def build(fn):
            return ChunkPipeline([('warp', fn), ('encode', fn)])
        """,
        'u.py': _stage_registry_uses(),
    }, rules=['KTPU507'])
    assert rule_ids(rep) == {'KTPU507'}
    assert any("'warp'" in f.message for f in rep.active)


def test_ktpu507_dead_stage_entries(tmp_path):
    # a tree with no stage sites at all: every registry entry is dead
    rep = run(tmp_path, {'a.py': 'X = 1\n'}, rules=['KTPU507'])
    assert rule_ids(rep) == {'KTPU507'}
    from kyverno_tpu.analysis.catalog_pass import load_stage_registry
    assert len(rep.active) == len(load_stage_registry())


# -- KTPU508: partition key hygiene ------------------------------------------

def test_ktpu508_direct_whole_set_fingerprint(tmp_path):
    rep = run(tmp_path, {'ops/e.py': """\
    def build(cps, aot, packed):
        key = aot.executable_cache_key(
            policy_set_fingerprint(cps.policies), packed)
        return key
    """}, rules=['KTPU508'])
    assert rule_ids(rep) == {'KTPU508'}


def test_ktpu508_resolves_binding_in_enclosing_scope(tmp_path):
    # the ops/eval.py shape: the fingerprint binds in the builder
    # function, the cache-key call sits in a nested closure
    rep = run(tmp_path, {'ops/e.py': """\
    def build_evaluator(cps, aot):
        fingerprint = policy_set_fingerprint(cps.policies)

        def _compiled_for(packed):
            return aot.executable_cache_key(fingerprint, packed)
        return _compiled_for
    """}, rules=['KTPU508'])
    assert rule_ids(rep) == {'KTPU508'}


def test_ktpu508_compile_fingerprint_is_clean(tmp_path):
    rep = run(tmp_path, {'ops/e.py': """\
    def build_evaluator(cps, aot):
        from ..partition.keys import compile_fingerprint
        fingerprint = compile_fingerprint(cps)

        def _compiled_for(packed):
            return aot.executable_cache_key(fingerprint, packed)
        return _compiled_for
    """}, rules=['KTPU508'])
    assert not rep.active


def test_ktpu508_partition_package_is_exempt(tmp_path):
    # partition/ IS the sanctioned fingerprint authority: the
    # degenerate whole-set spelling inside it is the oracle path
    rep = run(tmp_path, {'partition/keys.py': """\
    def compile_fingerprint(cps, aot, packed):
        return aot.executable_cache_key(
            policy_set_fingerprint(cps.policies), packed)
    """}, rules=['KTPU508'])
    assert not rep.active


def test_ktpu508_parameter_fingerprint_undecidable(tmp_path):
    # a fingerprint arriving as a parameter resolves nowhere — the
    # one-level pass stays silent instead of guessing
    rep = run(tmp_path, {'ops/e.py': """\
    def lookup(aot, fingerprint, packed):
        return aot.executable_cache_key(fingerprint, packed)
    """}, rules=['KTPU508'])
    assert not rep.active


# every catalog fleet_scope'd metric written from parallel/ with its
# identity label — the clean state for the KTPU509 fixtures (a partial
# set would trip the dead-scope check for the missing metrics)
KTPU509_CLEAN = """\
def emit(reg, wall):
    reg.observe('kyverno_tpu_mesh_step_duration_seconds', wall,
                shard='0')
    reg.set_gauge('kyverno_tpu_mesh_shard_skew_ratio', 1.0,
                  mesh='data8')
    reg.inc('kyverno_tpu_mesh_collective_seconds_total', wall,
            mesh='data8')
    reg.inc('kyverno_tpu_mesh_padding_rows_total', 1.0, mesh='data8')
"""


def test_ktpu509_clean_mesh_writes(tmp_path):
    rep = run(tmp_path, {'parallel/mesh.py': KTPU509_CLEAN},
              rules=['KTPU509'])
    assert not rep.active


def test_ktpu509_parallel_write_without_scope(tmp_path):
    # an unscoped metric written from parallel/ loses per-process
    # attribution in the federation merge
    rep = run(tmp_path, {'parallel/mesh.py': KTPU509_CLEAN + """\

def bad(reg):
    reg.inc('kyverno_tpu_host_fallback_total')
"""}, rules=['KTPU509'])
    assert rule_ids(rep) == {'KTPU509'}
    assert any('no fleet_scope' in f.message for f in rep.active)


def test_ktpu509_scoped_write_missing_identity_label(tmp_path):
    missing = KTPU509_CLEAN.replace(
        "reg.inc('kyverno_tpu_mesh_collective_seconds_total', wall,\n"
        "            mesh='data8')",
        "reg.inc('kyverno_tpu_mesh_collective_seconds_total', wall)")
    rep = run(tmp_path, {'parallel/mesh.py': missing},
              rules=['KTPU509'])
    assert rule_ids(rep) == {'KTPU509'}
    assert any('mesh=' in f.message and 'collective' in f.message
               for f in rep.active)


def test_ktpu509_scoped_write_outside_parallel_still_needs_label(
        tmp_path):
    rep = run(tmp_path, {
        'parallel/mesh.py': KTPU509_CLEAN,
        'observability/x.py': """\
def leak(reg):
    reg.set_gauge('kyverno_tpu_mesh_shard_skew_ratio', 1.0)
"""}, rules=['KTPU509'])
    assert rule_ids(rep) == {'KTPU509'}


def test_ktpu509_label_splat_is_uncheckable_not_flagged(tmp_path):
    # **labels keys are unknowable statically — the pass must not guess
    splat = KTPU509_CLEAN + """\

def forward(reg, wall, labels):
    reg.inc('kyverno_tpu_mesh_collective_seconds_total', wall,
            **labels)
"""
    rep = run(tmp_path, {'parallel/mesh.py': splat}, rules=['KTPU509'])
    assert not rep.active


def test_ktpu509_dead_scope(tmp_path):
    # a declared fleet_scope with no parallel/ write site: the scope
    # promises identity labels nothing emits
    rep = run(tmp_path, {'a.py': KTPU509_CLEAN}, rules=['KTPU509'])
    assert rule_ids(rep) == {'KTPU509'}
    assert all('no parallel/ write site' in f.message
               for f in rep.active)
    assert len(rep.active) == 4  # one per scoped catalog metric


def test_ktpu509_module_constant_resolution(tmp_path):
    # names resolve through UPPER_CASE constants, including the
    # fleet.MESH_* attribute spelling used by parallel/mesh.py
    rep = run(tmp_path, {'parallel/mesh.py': """\
MESH_STEP_DURATION = 'kyverno_tpu_mesh_step_duration_seconds'
MESH_SHARD_SKEW = 'kyverno_tpu_mesh_shard_skew_ratio'
MESH_COLLECTIVE_SECONDS = 'kyverno_tpu_mesh_collective_seconds_total'
MESH_PADDING_ROWS = 'kyverno_tpu_mesh_padding_rows_total'


def emit(reg, fleet, wall):
    reg.observe(fleet.MESH_STEP_DURATION, wall, shard='1')
    reg.set_gauge(MESH_SHARD_SKEW, 1.0, mesh='data8')
    reg.inc(MESH_COLLECTIVE_SECONDS, wall, mesh='data8')
    reg.inc(MESH_PADDING_ROWS, 2.0, mesh='data8')
"""}, rules=['KTPU509'])
    assert not rep.active


# -- KTPU00x: suppression hygiene (meta rules) -------------------------------

def test_ktpu001_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    X = 1  # ktpu: noqa[KTPU101]
    """}, rules=['KTPU001'])
    assert rule_ids(rep) == {'KTPU001'}
    rep = run(tmp_path, {'a.py': """\
    X = 1  # ktpu: noqa[KTPU101] -- justified example
    """}, rules=['KTPU001'])
    assert not rep.active


def test_ktpu002_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    X = 1  # ktpu: noqa[KTPU101] -- suppresses nothing
    """}, rules=['KTPU002'])
    assert rule_ids(rep) == {'KTPU002'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        return t.item()  # ktpu: noqa[KTPU101] -- fixture host sync
    jf = jax.jit(f)
    """}, rules=['KTPU101', 'KTPU002'])
    assert not rep.active
    assert [f.rule_id for f in rep.suppressed] == ['KTPU101']


# -- suppression semantics ---------------------------------------------------

def test_noqa_suppresses_only_listed_rule(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        return t.item()  # ktpu: noqa[KTPU203] -- wrong rule id
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert rule_ids(rep) == {'KTPU101'}


def test_noqa_comment_block_above(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t):
        # ktpu: noqa[KTPU101] -- wrapped reason text continues on
        # the next comment line without breaking the suppression
        return t.item()
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert not rep.active
    assert len(rep.suppressed) == 1


def test_noqa_in_docstring_is_inert(tmp_path):
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + '''\
    def f(t):
        """Docs may quote `# ktpu: noqa[KTPU101] -- like so`."""
        return t.item()
    jf = jax.jit(f)
    '''}, rules=['KTPU101'])
    assert rule_ids(rep) == {'KTPU101'}


# -- baseline round-trip -----------------------------------------------------

BAD_SRC = """\
import jax
import jax.numpy as jnp

def f(t):
    return t.item()
jf = jax.jit(f)
"""

FIXED_SRC = """\
import jax
import jax.numpy as jnp

def f(t):
    return jnp.sum(t)
jf = jax.jit(f)
"""

DRIFTED_SRC = """\
import jax
import jax.numpy as jnp

PAD = 1

def f(t):
    return t.item()
jf = jax.jit(f)
"""


def test_baseline_round_trip(tmp_path):
    bl = str(tmp_path / 'baseline.json')
    rep = run(tmp_path, {'a.py': BAD_SRC}, rules=['KTPU101'])
    assert len(rep.active) == 1
    write_baseline(bl, rep.active, reason='grandfathered in the test')
    rep2 = run(tmp_path, {'a.py': BAD_SRC}, rules=['KTPU101'],
               baseline=bl)
    assert not rep2.active
    assert len(rep2.baselined) == 1
    assert not rep2.stale_baseline
    assert not rep2.errors


def test_baseline_stale_entry_detected(tmp_path):
    bl = str(tmp_path / 'baseline.json')
    rep = run(tmp_path, {'a.py': BAD_SRC}, rules=['KTPU101'])
    write_baseline(bl, rep.active, reason='grandfathered in the test')
    rep2 = run(tmp_path, {'a.py': FIXED_SRC}, rules=['KTPU101'],
               baseline=bl)
    assert not rep2.active
    assert len(rep2.stale_baseline) == 1


def test_baseline_requires_justification(tmp_path):
    bl = tmp_path / 'baseline.json'
    bl.write_text(json.dumps({'entries': [
        {'rule': 'KTPU101', 'path': 'a.py', 'match': 'return t.item()',
         'reason': ''}]}))
    rep = run(tmp_path, {'a.py': BAD_SRC}, rules=['KTPU101'],
              baseline=str(bl))
    assert rep.errors  # unjustified entry is an error even if it matches


def test_baseline_survives_line_drift(tmp_path):
    bl = str(tmp_path / 'baseline.json')
    rep = run(tmp_path, {'a.py': BAD_SRC}, rules=['KTPU101'])
    write_baseline(bl, rep.active, reason='grandfathered in the test')
    rep2 = run(tmp_path, {'a.py': DRIFTED_SRC}, rules=['KTPU101'],
               baseline=bl)
    assert not rep2.active
    assert len(rep2.baselined) == 1


# -- registry hygiene --------------------------------------------------------

def test_rule_registry_complete():
    expected = {'KTPU001', 'KTPU002', 'KTPU101', 'KTPU102', 'KTPU103',
                'KTPU201', 'KTPU202', 'KTPU203', 'KTPU204', 'KTPU205',
                'KTPU301', 'KTPU302', 'KTPU303', 'KTPU304',
                'KTPU401', 'KTPU402',
                'KTPU501', 'KTPU502', 'KTPU503', 'KTPU504', 'KTPU505',
                'KTPU506', 'KTPU507', 'KTPU508', 'KTPU509',
                'KTPU601', 'KTPU602', 'KTPU603', 'KTPU604'}
    assert set(RULES) == expected
    for rid, rule in RULES.items():
        assert rule.summary.strip(), rid


def test_knob_table_renders_every_knob():
    from kyverno_tpu.analysis.knobs import render_knob_table
    table = render_knob_table()
    for name in KNOBS:
        assert f'`{name}`' in table


# -- v2 call graph: qualified resolution -------------------------------------

def test_callgraph_alias_import(tmp_path):
    """`import helpers as h; h.helper(t)` resolves across files — the
    finding lands in the helper's module."""
    rep = run(tmp_path, {
        'helpers.py': """\
    def helper(t):
        return t.tolist()
    """,
        'entry.py': JIT_PRELUDE + """\
    import helpers as h

    def f(t):
        return h.helper(t)
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert rule_ids(rep) == {'KTPU101'}
    assert {f.path for f in rep.active} == {'helpers.py'}


def test_callgraph_class_method_dispatch(tmp_path):
    """`self.m()` and assignment-typed receivers dispatch to the
    owning class's method, one level deep."""
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    class Evaluator:
        def prep(self, t):
            return t.tolist()

        def run(self, t):
            return self.prep(t)

    ev = Evaluator()

    def f(t):
        return ev.run(t)
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert rule_ids(rep) == {'KTPU101'}
    # per-class dispatch is authoritative: a same-name method on an
    # unrelated class must NOT be pulled into the graph
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    class A:
        def go(self, t):
            return t

    class B:
        def go(self, t):
            return t.tolist()

    a = A()

    def f(t):
        return a.go(t)
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert not rep.active


def test_callgraph_diamond_chain(tmp_path):
    """f -> a -> d and f -> b -> d: the shared sink is analyzed (and
    reported) exactly once."""
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def d(t):
        return t.item()

    def a(t):
        return d(t)

    def b(t):
        return d(t)

    def f(t):
        return a(t) + b(t)
    jf = jax.jit(f)
    """}, rules=['KTPU101'])
    assert len(rep.active) == 1
    assert rep.active[0].rule_id == 'KTPU101'


# -- v2 param-rooted taint ---------------------------------------------------

def test_taint_entry_param(tmp_path):
    """A non-static jit entry param is a tracer: casting it anywhere
    is a finding, and static_argnums exempts exactly that param."""
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t, n):
        return t * int(n)
    jf = jax.jit(f)
    """}, rules=['KTPU102'])
    assert rule_ids(rep) == {'KTPU102'}
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def f(t, n):
        return t * int(n)
    jf = jax.jit(f, static_argnums=(1,))
    """}, rules=['KTPU102'])
    assert not rep.active


def test_taint_depth_boundary(tmp_path):
    """Default KTPU_LINT_TAINT_DEPTH=3: a cast of a param three call
    edges below the entry fires; four edges down, taint has stopped."""
    chain = JIT_PRELUDE + """\
    def h3(x):
        return int(x)

    def h2(x):
        return h3(x)

    def h1(x):
        return h2(x)

    def f(t):
        return h1(t)
    jf = jax.jit(f)
    """
    rep = run(tmp_path, {'a.py': chain}, rules=['KTPU102'])
    assert rule_ids(rep) == {'KTPU102'}
    assert 'call chain' in rep.active[0].message
    deeper = chain.replace('def h3(x):\n        return int(x)',
                           'def h4(x):\n'
                           '        return int(x)\n\n'
                           '    def h3(x):\n'
                           '        return h4(x)')
    rep = run(tmp_path, {'a.py': deeper}, rules=['KTPU102'])
    assert not rep.active


def test_taint_depth_knob(tmp_path, monkeypatch):
    """KTPU_LINT_TAINT_DEPTH tightens the propagation bound."""
    monkeypatch.setenv('KTPU_LINT_TAINT_DEPTH', '1')
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def h2(x):
        return int(x)

    def h1(x):
        return h2(x)

    def f(t):
        return h1(t)
    jf = jax.jit(f)
    """}, rules=['KTPU102'])
    assert not rep.active  # the cast sits at depth 2, past the bound


def test_callgraph_real_world_miss(tmp_path):
    """Planted miss modeled on ops/eval.py before the tuple-freeze fix
    (PR 4): the tracer-concretizing branch lives two helpers below the
    jit entry, where the old one-level pass could not see it."""
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    def _threshold(counts):
        if counts > 0:
            return counts
        return 0

    def _score(batch):
        return _threshold(batch)

    def eval_batch(batch):
        return _score(batch)
    jf = jax.jit(eval_batch)
    """}, rules=['KTPU103'])
    assert rule_ids(rep) == {'KTPU103'}
    [f] = rep.active
    assert '_threshold' in f.message
    assert 'call chain' in f.message


def test_ktpu201_self_attr_closure(tmp_path):
    """A jitted *method* closing over a mutable `self.X` container is
    the same stale-closure hazard as a module global (the old pass
    only saw bare names)."""
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    class Model:
        def __init__(self):
            self.table = {}

        def step(self, t):
            return t + len(self.table)

    m = Model()
    jstep = jax.jit(m.step)
    """}, rules=['KTPU201'])
    assert rule_ids(rep) == {'KTPU201'}
    assert 'self.table' in rep.active[0].message
    rep = run(tmp_path, {'a.py': JIT_PRELUDE + """\
    class Model:
        def __init__(self):
            self.table = (1, 2)

        def step(self, t):
            return t + len(self.table)

    m = Model()
    jstep = jax.jit(m.step)
    """}, rules=['KTPU201'])
    assert not rep.active  # a tuple attribute cannot drift


# -- KTPU6xx: concurrency discipline -----------------------------------------

def test_ktpu601_positive_negative(tmp_path):
    pos = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
            threading.Thread(target=self._run).start()

        def _run(self):
            self.n = 1

        def bump(self):
            with self._lock:
                self.n = 2
    """
    rep = run(tmp_path, {'a.py': pos}, rules=['KTPU601'])
    assert rule_ids(rep) == {'KTPU601'}
    rep = run(tmp_path, {'a.py': pos.replace(
        '        def _run(self):\n            self.n = 1',
        '        def _run(self):\n'
        '            with self._lock:\n'
        '                self.n = 1')}, rules=['KTPU601'])
    assert not rep.active


def test_ktpu602_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    import threading

    def worker():
        with stage('encode'):
            pass

    def start():
        t = threading.Thread(target=worker)
        t.start()
    """}, rules=['KTPU602'])
    assert rule_ids(rep) == {'KTPU602'}
    rep = run(tmp_path, {'a.py': """\
    import threading

    def worker():
        install_capture(None)
        with stage('encode'):
            pass

    def start():
        t = threading.Thread(target=worker)
        t.start()
    """}, rules=['KTPU602'])
    assert not rep.active


def test_ktpu603_positive_negative(tmp_path):
    pos = """\
    G = 'kyverno_tpu_queue_depth'

    def loop(reg, q):
        while True:
            reg.set_gauge(G, float(len(q)))
    """
    rep = run(tmp_path, {'a.py': pos}, rules=['KTPU603'])
    assert rule_ids(rep) == {'KTPU603'}
    rep = run(tmp_path, {'a.py': pos + """\

    def setup(reg):
        reg.mark_reset_on_close(G)
    """}, rules=['KTPU603'])
    assert not rep.active


def test_ktpu604_positive_negative(tmp_path):
    rep = run(tmp_path, {'a.py': """\
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with B:
            with A:
                pass
    """}, rules=['KTPU604'])
    assert rule_ids(rep) == {'KTPU604'}
    rep = run(tmp_path, {'a.py': """\
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with A:
            with B:
                pass
    """}, rules=['KTPU604'])
    assert not rep.active
