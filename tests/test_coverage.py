"""Device-coverage ledger: attributed host-fallback telemetry across
compile time (per-rule placement) and runtime (host-replay counters,
per-scan coverage ratio), the /debug/coverage endpoint, the CLI report,
and the no-op-until-configured contract."""

import json
import os
import sys
import threading

import pytest

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.observability import coverage
from kyverno_tpu.observability import tracing
from kyverno_tpu.observability.metrics import (MetricsRegistry,
                                               set_global_registry)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    'scripts'))

NO_AUTOGEN = {'pod-policies.kyverno.io/autogen-controllers': 'none'}

#: fully device-compiled pattern rule
DEVICE_POL = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'dev-pol', 'annotations': dict(NO_AUTOGEN)},
    'spec': {'rules': [
        {'name': 'check-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'app label required',
                      'pattern': {'metadata': {'labels': {'app': '?*'}}}}},
    ]}}

#: device-compiled, but the general-wildcard DP is only exact inside the
#: 64-byte string window — longer label values read STATUS_HOST
DP_POL = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'dp-pol', 'annotations': dict(NO_AUTOGEN)},
    'spec': {'rules': [
        {'name': 'dp-rule',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'tier must thread x then y',
                      'pattern': {'metadata': {'labels':
                                               {'tier': '*x*y*'}}}}},
    ]}}

#: deprecated In operator → CompileError(unsupported_operator) → the
#: whole policy runs on the host engine
HOST_POL = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'host-pol', 'annotations': dict(NO_AUTOGEN)},
    'spec': {'rules': [
        {'name': 'legacy-in',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'validate': {'message': 'ns check', 'deny': {'conditions': [
             {'key': '{{ request.object.metadata.namespace }}',
              'operator': 'In', 'value': ['kube-system']}]}}},
    ]}}

MUTATE_REPLACE_POL = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'mutate-replace',
                 'annotations': dict(NO_AUTOGEN)},
    'spec': {'rules': [
        {'name': 'replace-app',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'mutate': {'patchesJson6902':
                    '- op: replace\n  path: /metadata/labels/app\n'
                    '  value: fixed\n'}},
    ]}}

MUTATE_FOREACH_POL = {
    'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
    'metadata': {'name': 'pull-policy', 'annotations': dict(NO_AUTOGEN)},
    'spec': {'rules': [
        {'name': 'set-pull-policy',
         'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
         'mutate': {'foreach': [
             {'list': 'request.object.spec.containers',
              'patchStrategicMerge': {'spec': {'containers': [
                  {'name': '{{ element.name }}',
                   'imagePullPolicy': 'IfNotPresent'}]}}}]}}
    ]}}


def pod(i, tier=None, names=('c0',), app=True):
    labels = {}
    if app and i % 2:
        labels['app'] = 'x'
    if tier is not None:
        labels['tier'] = tier
    meta = {'name': f'p{i}', 'namespace': 'default'}
    if labels:
        meta['labels'] = labels
    return {'apiVersion': 'v1', 'kind': 'Pod', 'metadata': meta,
            'spec': {'containers': [{'name': n, 'image': 'nginx:1'}
                                    for n in names]}}


def mixed_resources():
    out = [pod(i) for i in range(6)]
    out.append(pod(10, tier='axby'))            # DP decidable in-window
    out.append(pod(11, tier='a' * 80 + 'xzy'))  # overflows → STATUS_HOST
    return out


@pytest.fixture
def ledger():
    reg = MetricsRegistry()
    led = coverage.configure(reg)
    yield led, reg
    coverage.disable()


def mixed_scanner():
    from kyverno_tpu.compiler.scan import BatchScanner
    return BatchScanner([Policy(DEVICE_POL), Policy(DP_POL),
                         Policy(HOST_POL)])


class TestMixedScan:
    def test_attributed_coverage(self, ledger):
        led, reg = ledger
        scanner = mixed_scanner()
        scanner.scan(mixed_resources())
        # ratio strictly inside (0, 1): some rows device, some host
        ratio = reg.gauge_value('kyverno_tpu_device_coverage_ratio')
        assert 0.0 < ratio < 1.0
        # the overflowing DP row is attributed as status_host …
        assert reg.counter_value(
            'kyverno_tpu_host_fallback_total', path='validate',
            reason='status_host') >= 1
        # … and the host policy's replayed rows as unsupported_operator
        assert reg.counter_value(
            'kyverno_tpu_host_fallback_total', path='validate',
            reason='unsupported_operator') >= 1
        # no reason escapes the taxonomy for the exercised sites
        text = reg.render()
        assert 'reason="unknown"' not in text
        from kyverno_tpu.observability.catalog import METRICS
        for (path, reason), _rows in led._fallbacks.items():
            assert reason in coverage.REASONS, (path, reason)
        assert 'kyverno_tpu_host_fallback_total' in METRICS
        # ledger invariant (what bench.py asserts before writing output)
        totals = led.totals()
        assert totals['device_rows'] + totals['host_rows'] == \
            totals['total_rows']

    def test_placement_records(self, ledger):
        led, reg = ledger
        scanner = mixed_scanner()
        scanner.scan(mixed_resources())
        rules = {(r['policy'], r['rule']): r
                 for r in led.report()['rules']}
        assert rules[('dev-pol', 'check-app')]['placement'] == 'device'
        assert rules[('dev-pol', 'check-app')]['effective'] == 'device'
        dp = rules[('dp-pol', 'dp-rule')]
        assert dp['placement'] == 'device'
        assert dp['effective'] == 'partial'  # observed host rows
        assert dp['host_rows'] >= 1 and dp['device_rows'] >= 1
        host = rules[('host-pol', 'legacy-in')]
        assert host['placement'] == 'host'
        assert host['reason'] == 'unsupported_operator'
        assert 'not vectorized' in host['detail']
        # placement gauge series exist with the effective placement
        assert reg.gauge_value(
            'kyverno_tpu_rule_placement_info', policy='dp-pol',
            rule='dp-rule', path='validate', placement='partial',
            reason='') == 1.0
        assert reg.gauge_value(
            'kyverno_tpu_rule_placement_info', policy='host-pol',
            rule='legacy-in', path='validate', placement='host',
            reason='unsupported_operator') == 1.0

    def test_policy_coupling_override(self, ledger):
        """A device-compilable rule sharing a policy with a host rule is
        placed host with reason=policy_coupling."""
        led, _reg = ledger
        coupled = {
            'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
            'metadata': {'name': 'coupled',
                         'annotations': dict(NO_AUTOGEN)},
            'spec': {'rules': [
                dict(DEVICE_POL['spec']['rules'][0]),
                dict(HOST_POL['spec']['rules'][0]),
            ]}}
        from kyverno_tpu.compiler.scan import BatchScanner
        BatchScanner([Policy(coupled)])
        rules = {(r['policy'], r['rule']): r
                 for r in led.report()['rules']}
        rec = rules[('coupled', 'check-app')]
        assert rec['placement'] == 'host'
        assert rec['reason'] == 'policy_coupling'

    def test_report_span_carries_ratio(self, ledger):
        _led, _reg = ledger
        from kyverno_tpu.observability import device as devtel
        mem = tracing.configure()
        devtel.configure(MetricsRegistry())
        try:
            scanner = mixed_scanner()
            scanner.scan(mixed_resources())
            spans = [s for s in mem.spans()
                     if s.name == 'kyverno/device/report'
                     and 'device_coverage_ratio' in s.attributes]
            assert spans, 'report span missing device_coverage_ratio'
            ratio = spans[-1].attributes['device_coverage_ratio']
            assert 0.0 < ratio < 1.0
        finally:
            devtel.disable()
            tracing.disable()

    def test_bit_identical_with_ledger_on_vs_off(self):
        """The ledger only observes: responses (statuses AND messages)
        are byte-identical with coverage enabled vs disabled."""
        resources = mixed_resources()

        def snapshot():
            out = mixed_scanner().scan(resources)
            return [[(resp.policy_response.policy_name, rr.name,
                      str(rr.status), rr.message)
                     for resp in row for rr in resp.policy_response.rules]
                    for row in out]

        coverage.disable()
        baseline = snapshot()
        coverage.configure(MetricsRegistry())
        try:
            with_ledger = snapshot()
        finally:
            coverage.disable()
        assert with_ledger == baseline


class TestMutateFallbacks:
    def test_attributed_reasons(self, ledger):
        led, reg = ledger
        from kyverno_tpu.compiler.apply import BatchApplier
        applier = BatchApplier([Policy(MUTATE_REPLACE_POL),
                                Policy(MUTATE_FOREACH_POL)], processes=0)
        docs = [pod(0, app=False), pod(1),   # no labels → replace missing
                pod(2, names=('a', 'a'))]    # duplicate element names
        applier.apply(docs, parallel=False)
        assert reg.counter_value(
            'kyverno_tpu_host_fallback_total', path='mutate',
            reason='replace_path_missing') >= 1
        assert reg.counter_value(
            'kyverno_tpu_host_fallback_total', path='mutate',
            reason='duplicate_element_names') >= 1
        assert 'reason="unknown"' not in reg.render()
        rules = {(r['policy'], r['rule'], r['path']): r
                 for r in led.report()['rules']}
        rec = rules[('mutate-replace', 'replace-app', 'mutate')]
        assert rec['placement'] == 'device'   # compiled fast applier
        assert rec['effective'] == 'partial'  # observed escapes
        assert rec['host_rows'] >= 1


class TestEndpointAndCli:
    def test_debug_coverage_agrees_with_cli(self, ledger, tmp_path):
        import urllib.request
        import yaml
        from kyverno_tpu.observability.profiling import ProfilingServer
        scanner = mixed_scanner()
        scanner.scan(mixed_resources())
        server = ProfilingServer(port=0)
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/debug/coverage',
                    timeout=10) as resp:
                live = json.loads(resp.read().decode())
        finally:
            server.stop()
        assert live['enabled'] is True
        pack = tmp_path / 'pack.yaml'
        pack.write_text(yaml.safe_dump_all(
            [DEVICE_POL, DP_POL, HOST_POL]))
        import coverage_report
        cli = coverage_report.compile_report(
            coverage_report.load_policies([str(pack)]))
        cli_rules = {(r['policy'], r['rule'], r['path']):
                     (r['placement'], r['reason']) for r in cli['rules']}
        live_rules = {(r['policy'], r['rule'], r['path']):
                      (r['placement'], r['reason'])
                      for r in live['rules']}
        # compile-time placement must agree exactly, rule for rule
        assert cli_rules == live_rules
        # and the live view additionally carries runtime row counts
        dp = [r for r in live['rules'] if r['rule'] == 'dp-rule'][0]
        assert dp['effective'] == 'partial'

    def test_endpoint_reports_disabled(self):
        import urllib.request
        from kyverno_tpu.observability.profiling import ProfilingServer
        coverage.disable()
        server = ProfilingServer(port=0)
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f'http://127.0.0.1:{port}/debug/coverage',
                    timeout=10) as resp:
                body = json.loads(resp.read().decode())
        finally:
            server.stop()
        assert body == {'enabled': False}


class TestNoopWhenUnconfigured:
    def test_mixed_scan_creates_nothing(self):
        """The acceptance no-op contract: an unconfigured process doing
        a mixed device/host scan creates zero coverage series, spans,
        or threads."""
        coverage.disable()
        tracing.disable()
        sentinel = MetricsRegistry()
        set_global_registry(sentinel)
        before = set(threading.enumerate())
        try:
            scanner = mixed_scanner()
            scanner.scan(mixed_resources())
            from kyverno_tpu.compiler.apply import BatchApplier
            applier = BatchApplier([Policy(MUTATE_REPLACE_POL)],
                                   processes=0)
            applier.apply([pod(0, app=False)], parallel=False)
        finally:
            set_global_registry(None)
        assert coverage.ledger() is None
        assert coverage.last_ratio() is None
        assert coverage.scan_tally() is None
        text = sentinel.render()
        assert 'kyverno_tpu_host_fallback_total' not in text
        assert 'kyverno_tpu_device_coverage_ratio' not in text
        assert 'kyverno_tpu_rule_placement_info' not in text
        assert tracing.memory_exporter() is None
        # no coverage-owned thread survives (the ledger never spawns
        # any; only the scan pipeline's own executors may appear)
        after = {t for t in threading.enumerate() if t not in before}
        assert not any('coverage' in t.name for t in after)


class TestRenderHelp:
    def test_help_lines_from_catalog(self):
        reg = MetricsRegistry()
        reg.inc('kyverno_tpu_host_fallback_total', path='validate',
                reason='status_host')
        reg.set_gauge('kyverno_tpu_device_coverage_ratio', 0.5)
        text = reg.render()
        from kyverno_tpu.observability.catalog import METRICS
        assert ('# HELP kyverno_tpu_host_fallback_total '
                + METRICS['kyverno_tpu_host_fallback_total'].help) in text
        # HELP precedes TYPE for the same metric (prometheus convention)
        lines = text.splitlines()
        h = lines.index('# HELP kyverno_tpu_device_coverage_ratio '
                        + METRICS['kyverno_tpu_device_coverage_ratio'].help)
        assert lines[h + 1] == \
            '# TYPE kyverno_tpu_device_coverage_ratio gauge'
