"""Report controllers: metadata cache → batched background scan with
last-scan-time resumability → aggregation into PolicyReports
(reference: pkg/controllers/report)."""

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.dclient.client import FakeClient
from kyverno_tpu.reports.aggregate import AggregateController
from kyverno_tpu.reports.controllers import (ANNOTATION_LAST_SCAN_TIME,
                                             AdmissionReportController,
                                             BackgroundScanController,
                                             MetadataCache,
                                             ResourceController)

POLICY = yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: audit
  rules:
    - name: team-label
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: team label required
        pattern:
          metadata:
            labels:
              team: "?*"
""")


def pod(name, team=None, uid=None):
    labels = {'team': team} if team else {}
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'uid': uid or f'uid-{name}', 'labels': labels},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


def make_world():
    client = FakeClient()
    client.create_resource('kyverno.io/v1', 'ClusterPolicy', '', POLICY)
    client.create_resource('v1', 'Pod', 'default', pod('good', team='infra'))
    client.create_resource('v1', 'Pod', 'default', pod('bad'))
    return client


class TestScanPipeline:
    def test_scan_writes_reports_and_aggregates(self):
        client = make_world()
        cache = MetadataCache()
        resource_ctrl = ResourceController(client, cache)
        scan_ctrl = BackgroundScanController(client, [Policy(POLICY)],
                                             cache=cache)
        resource_ctrl.update_policies([Policy(POLICY)])
        for changed in resource_ctrl.sync():
            scan_ctrl.enqueue(changed)
        reports = scan_ctrl.reconcile()
        assert len(reports) == 2
        stored = client.list_resource('kyverno.io/v1alpha2',
                                      'BackgroundScanReport', 'default', None)
        assert len(stored) == 2
        for r in stored:
            assert ANNOTATION_LAST_SCAN_TIME in r['metadata']['annotations']
        from kyverno_tpu.reports.results import get_results
        results = {r['metadata']['ownerReferences'][0]['name']:
                   get_results(r) for r in stored}
        assert results['good'][0]['result'] == 'pass'
        assert results['bad'][0]['result'] == 'fail'
        # aggregate → PolicyReport
        agg = AggregateController(client)
        agg.reconcile()
        prs = client.list_resource('wgpolicyk8s.io/v1alpha2',
                                   'PolicyReport', 'default', None)
        assert prs
        summary = prs[0].get('summary') or {}
        assert summary.get('pass') == 1 and summary.get('fail') == 1

    def test_last_scan_time_resumability(self):
        client = make_world()
        scan_ctrl = BackgroundScanController(client, [Policy(POLICY)])
        p = pod('good', team='infra')
        scan_ctrl.enqueue(p)
        assert len(scan_ctrl.reconcile()) == 1
        # unchanged resource: skipped
        scan_ctrl.enqueue(p)
        assert scan_ctrl.reconcile() == []
        # changed resource: rescanned
        p2 = pod('good')  # team label dropped
        scan_ctrl.enqueue(p2)
        assert len(scan_ctrl.reconcile()) == 1

    def test_policy_change_invalidates_scans(self):
        client = make_world()
        scan_ctrl = BackgroundScanController(client, [Policy(POLICY)])
        p = pod('good', team='infra')
        scan_ctrl.enqueue(p)
        scan_ctrl.reconcile()
        scan_ctrl.set_policies([Policy(POLICY)])  # policy event
        scan_ctrl.enqueue(p)
        assert len(scan_ctrl.reconcile()) == 1  # re-scanned


class TestAdmissionReportDedup:
    def test_merges_by_uid(self):
        client = FakeClient()
        for i in range(3):
            client.create_resource('kyverno.io/v1alpha2', 'AdmissionReport',
                                   'default', {
                'apiVersion': 'kyverno.io/v1alpha2',
                'kind': 'AdmissionReport',
                'metadata': {
                    'name': f'rep-{i}', 'namespace': 'default',
                    'creationTimestamp': f'2026-01-0{i+1}T00:00:00Z',
                    'labels': {'audit.kyverno.io/resource.uid': 'u1'}},
                'spec': {'results': [{'policy': 'p', 'rule': f'r{i}',
                                      'result': 'pass',
                                      'source': 'kyverno'}]},
            })
        ctrl = AdmissionReportController(client)
        assert ctrl.reconcile() == 1
        left = client.list_resource('kyverno.io/v1alpha2',
                                    'AdmissionReport', 'default', None)
        assert len(left) == 1
        from kyverno_tpu.reports.results import get_results
        assert len(get_results(left[0])) == 3
        assert left[0]['spec']['summary']['pass'] == 3
