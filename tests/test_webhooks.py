"""Admission serving layer: end-to-end AdmissionReview round trips
through the handler chain (reference behaviors: pkg/webhooks)."""

import json

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.config.config import Configuration
from kyverno_tpu.policycache.cache import Cache
from kyverno_tpu.webhooks import admission
from kyverno_tpu.webhooks.handlers import ResourceHandlers
from kyverno_tpu.webhooks.server import WebhookServer

ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-labels
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""

AUDIT_POLICY = ENFORCE_POLICY.replace(
    'enforce', 'audit').replace('require-labels', 'audit-labels')

MUTATE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: add-default-label
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: add-managed
      match: {any: [{resources: {kinds: [Pod]}}]}
      mutate:
        patchStrategicMerge:
          metadata:
            labels:
              +(managed): "yes"
"""


def make_cache(*policy_yamls):
    cache = Cache()
    policies = [Policy(d) for y in policy_yamls
                for d in yaml.safe_load_all(y)]
    cache.warm_up(policies)
    return cache


def pod(labels=None, name='test-pod'):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': 'default',
                         'labels': labels or {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


def review(resource, operation='CREATE', old=None):
    return {
        'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
        'request': {
            'uid': 'uid-1',
            'kind': {'group': '', 'version': 'v1',
                     'kind': resource.get('kind', '')},
            'namespace': (resource.get('metadata') or {}).get(
                'namespace', ''),
            'name': (resource.get('metadata') or {}).get('name', ''),
            'operation': operation,
            'object': resource,
            'oldObject': old,
            'userInfo': {'username': 'alice', 'groups': []},
        },
    }


def serve(cache, **kwargs):
    handlers = ResourceHandlers(cache, **kwargs)
    return WebhookServer(handlers, configuration=Configuration())


class TestValidateWebhook:
    def test_enforce_denies_with_blocked_message(self):
        server = serve(make_cache(ENFORCE_POLICY))
        body = server.handle('/validate/fail',
                             json.dumps(review(pod())).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        msg = resp['status']['message']
        assert 'require-labels' in msg
        assert 'require-team' in msg
        assert 'validation error' in msg and 'team' in msg
        assert msg.startswith('\n\npolicy Pod/default/test-pod')

    def test_enforce_allows_compliant(self):
        server = serve(make_cache(ENFORCE_POLICY))
        body = server.handle(
            '/validate/fail',
            json.dumps(review(pod({'team': 'infra'}))).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is True
        assert 'warnings' not in resp

    def test_audit_mode_allows_and_reports(self):
        audits = []
        handlers = ResourceHandlers(
            make_cache(AUDIT_POLICY),
            audit_sink=lambda req, responses: audits.append(req))
        server = WebhookServer(handlers)
        body = server.handle('/validate',
                             json.dumps(review(pod())).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is True
        assert audits  # audit hand-off happened
        # the audit path evaluates audit-mode policies
        audit_responses = handlers.audit_responses(
            review(pod())['request'])
        assert audit_responses
        assert audit_responses[0].is_failed()


class TestMutateWebhook:
    def test_mutation_patch_applies(self):
        server = serve(make_cache(MUTATE_POLICY))
        body = server.handle('/mutate',
                             json.dumps(review(pod())).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is True
        patches = admission.decode_patch(resp)
        assert any(p.get('path', '').endswith('managed') or
                   'managed' in str(p.get('value', '')) for p in patches)

    def test_no_mutation_when_present(self):
        server = serve(make_cache(MUTATE_POLICY))
        body = server.handle(
            '/mutate',
            json.dumps(review(pod({'managed': 'no'}))).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is True
        assert admission.decode_patch(resp) == []


class TestMiddleware:
    def test_filter_excludes_configured_resources(self):
        config = Configuration()
        config.load({'data': {'resourceFilters':
                              '[Pod,default,excluded-*]'}})
        handlers = ResourceHandlers(make_cache(ENFORCE_POLICY),
                                    configuration=config)
        server = WebhookServer(handlers, configuration=config)
        body = server.handle(
            '/validate/fail',
            json.dumps(review(pod(name='excluded-pod'))).encode())
        assert json.loads(body)['response']['allowed'] is True
        body = server.handle(
            '/validate/fail',
            json.dumps(review(pod(name='other-pod'))).encode())
        assert json.loads(body)['response']['allowed'] is False

    def test_protection_denies_managed_edits(self):
        handlers = ResourceHandlers(make_cache())
        server = WebhookServer(handlers, protection_enabled=True)
        managed = pod()
        managed['metadata']['labels'] = {
            'app.kubernetes.io/managed-by': 'kyverno'}
        body = server.handle('/validate',
                             json.dumps(review(managed)).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        assert 'kyverno managed resource' in resp['status']['message']


class TestPolicyAdmission:
    def test_valid_policy_accepted(self):
        server = serve(make_cache())
        doc = next(yaml.safe_load_all(ENFORCE_POLICY))
        body = server.handle('/policyvalidate',
                             json.dumps(review(doc)).encode())
        assert json.loads(body)['response']['allowed'] is True

    def test_invalid_policy_rejected(self):
        server = serve(make_cache())
        doc = next(yaml.safe_load_all(ENFORCE_POLICY))
        doc['spec']['rules'][0].pop('validate')
        body = server.handle('/policyvalidate',
                             json.dumps(review(doc)).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        assert 'exactly one of' in resp['status']['message']

    def test_background_userinfo_var_rejected(self):
        server = serve(make_cache())
        doc = next(yaml.safe_load_all(ENFORCE_POLICY))
        doc['spec']['rules'][0]['validate']['message'] = \
            '{{request.userInfo.username}} may not do this'
        body = server.handle('/policyvalidate',
                             json.dumps(review(doc)).encode())
        resp = json.loads(body)['response']
        assert resp['allowed'] is False
        assert 'is not allowed' in resp['status']['message']

    def test_exception_validation(self):
        server = serve(make_cache())
        ex = {'apiVersion': 'kyverno.io/v2alpha1',
              'kind': 'PolicyException',
              'metadata': {'name': 'x', 'namespace': 'default'},
              'spec': {'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
                       'exceptions': [{'policyName': 'p',
                                       'ruleNames': ['r']}]}}
        body = server.handle('/exceptionvalidate',
                             json.dumps(review(ex)).encode())
        assert json.loads(body)['response']['allowed'] is True
        ex['spec']['exceptions'] = []
        body = server.handle('/exceptionvalidate',
                             json.dumps(review(ex)).encode())
        assert json.loads(body)['response']['allowed'] is False


class TestHTTPServer:
    def test_http_round_trip_and_probes(self):
        import urllib.request
        server = serve(make_cache(ENFORCE_POLICY))
        server.port = 0  # ephemeral
        server.start()
        try:
            base = f'http://127.0.0.1:{server.port}'
            with urllib.request.urlopen(f'{base}/health/liveness') as r:
                assert r.status == 200
            with urllib.request.urlopen(f'{base}/health/readiness') as r:
                assert r.status == 200
            req = urllib.request.Request(
                f'{base}/validate/fail',
                data=json.dumps(review(pod())).encode(),
                headers={'Content-Type': 'application/json'})
            with urllib.request.urlopen(req) as r:
                resp = json.loads(r.read())['response']
            assert resp['allowed'] is False
        finally:
            server.stop()


class TestGenerateHandOff:
    def test_generate_policy_creates_update_request(self):
        urs = []
        generate_policy = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: add-networkpolicy
spec:
  rules:
    - name: default-deny
      match: {any: [{resources: {kinds: [Namespace]}}]}
      generate:
        apiVersion: networking.k8s.io/v1
        kind: NetworkPolicy
        name: default-deny
        namespace: "{{request.object.metadata.name}}"
        data:
          spec: {podSelector: {}, policyTypes: [Ingress]}
"""
        handlers = ResourceHandlers(make_cache(generate_policy),
                                    ur_sink=urs.append)
        server = WebhookServer(handlers)
        ns = {'apiVersion': 'v1', 'kind': 'Namespace',
              'metadata': {'name': 'team-a'}}
        body = server.handle('/validate', json.dumps(review(ns)).encode())
        assert json.loads(body)['response']['allowed'] is True
        assert urs and urs[0]['type'] == 'generate'
        assert urs[0]['policy'] == 'add-networkpolicy'


class TestDeviceAdmissionEquivalence:
    """The device fast path must produce the same admission decision and
    messages as the engine loop (operation context, userInfo vars)."""

    PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: ops-policy
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: only-create
      match: {any: [{resources: {kinds: [Pod], operations: [CREATE]}}]}
      preconditions:
        all:
          - key: "{{ request.operation }}"
            operator: Equals
            value: CREATE
      validate:
        message: "pods need team"
        pattern: {metadata: {labels: {team: "?*"}}}
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: user-policy
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  background: false
  rules:
    - name: no-bob
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "bob may not create pods"
        deny:
          conditions:
            all:
              - key: "{{ request.userInfo.username }}"
                operator: Equals
                value: bob
"""

    def _responses(self, device, username, labels):
        handlers = ResourceHandlers(make_cache(self.PACK), device=device)
        server = WebhookServer(handlers)
        r = review(pod(labels))
        r['request']['userInfo']['username'] = username
        body = server.handle('/validate/fail', json.dumps(r).encode())
        return json.loads(body)['response']

    def test_device_matches_engine_loop(self):
        for username in ('alice', 'bob'):
            for labels in ({}, {'team': 'x'}):
                dev = self._responses(True, username, labels)
                host = self._responses(False, username, labels)
                assert dev['allowed'] == host['allowed'], (username, labels)
                assert dev.get('status') == host.get('status'), \
                    (username, labels)
                assert dev.get('warnings') == host.get('warnings'), \
                    (username, labels)


HOST_ENFORCE_POLICY = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: host-require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  applyRules: One
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata:
            labels:
              team: "?*"
"""


class TestHostPolicyAdmissionScreen:
    def test_host_policy_enforced_on_device_admission_path(self):
        """A host-evaluated enforce policy (applyRules One keeps the
        whole policy on the host engine) must still deny through the
        device admission path — the host-policy pre-screen may only
        skip sets that genuinely cannot match (regression: the screen
        once passed the operation string as the matcher's
        policy_namespace argument, silently screening every host
        policy out of admission and admitting violations)."""
        import json as _json
        from kyverno_tpu.policycache.cache import VALIDATE_ENFORCE
        cache = make_cache(HOST_ENFORCE_POLICY)
        handlers = ResourceHandlers(cache, device=True)
        server = WebhookServer(handlers)
        assert handlers.wait_device_ready(cache.get_policies(
            VALIDATE_ENFORCE, 'Pod', 'default'))

        def review(labeled):
            doc = {'apiVersion': 'v1', 'kind': 'Pod',
                   'metadata': {'name': 'p', 'namespace': 'default',
                                'labels': {'team': 'sre'} if labeled
                                else {}},
                   'spec': {'containers': [{'name': 'c',
                                            'image': 'nginx:1'}]}}
            return _json.dumps({
                'apiVersion': 'admission.k8s.io/v1',
                'kind': 'AdmissionReview',
                'request': {'uid': 'u', 'operation': 'CREATE',
                            'kind': {'group': '', 'version': 'v1',
                                     'kind': 'Pod'},
                            'namespace': 'default', 'name': 'p',
                            'object': doc,
                            'userInfo': {'username': 't'}}}).encode()
        out = _json.loads(server.handle('/validate/fail', review(False)))
        assert out['response']['allowed'] is False
        assert 'team' in out['response']['status']['message']
        out = _json.loads(server.handle('/validate/fail', review(True)))
        assert out['response']['allowed'] is True


class TestMalformedReviewHardening:
    """Malformed bodies get a structured 400 AdmissionReview, and
    error-path traffic lands on the admission instruments."""

    def test_invalid_json_returns_structured_400(self):
        server = serve(make_cache(ENFORCE_POLICY))
        out, status = server.handle_request('/validate/fail',
                                            b'{not json!')
        assert status == 400
        resp = json.loads(out)
        assert resp['kind'] == 'AdmissionReview'
        assert resp['response']['allowed'] is False
        assert 'malformed' in resp['response']['status']['message']

    def test_missing_request_returns_structured_400(self):
        server = serve(make_cache(ENFORCE_POLICY))
        body = json.dumps({'apiVersion': 'admission.k8s.io/v1',
                           'kind': 'AdmissionReview'}).encode()
        out, status = server.handle_request('/validate/fail', body)
        assert status == 400
        resp = json.loads(out)['response']
        assert resp['allowed'] is False
        assert resp['uid'] == ''

    def test_non_dict_request_returns_structured_400(self):
        server = serve(make_cache(ENFORCE_POLICY))
        body = json.dumps({'request': ['not', 'a', 'dict']}).encode()
        out, status = server.handle_request('/validate/fail', body)
        assert status == 400
        assert json.loads(out)['response']['allowed'] is False

    def test_handle_keeps_bytes_contract(self):
        # the in-process entry point still returns bytes (and raises
        # KeyError for unknown routes)
        server = serve(make_cache(ENFORCE_POLICY))
        out = server.handle('/validate/fail', b'also not json')
        assert json.loads(out)['response']['allowed'] is False
        try:
            server.handle('/nope', b'{}')
        except KeyError:
            pass
        else:
            raise AssertionError('unknown route must raise KeyError')

    def test_malformed_and_exception_paths_record_error_metrics(self):
        from kyverno_tpu.observability.metrics import (
            ADMISSION_REQUESTS, MetricsRegistry, set_global_registry)
        from kyverno_tpu.webhooks.server import PolicyHandlers

        class BoomHandlers(PolicyHandlers):
            def validate(self, request):
                raise RuntimeError('boom')

        handlers = ResourceHandlers(make_cache(ENFORCE_POLICY))
        server = WebhookServer(handlers, policy_handlers=BoomHandlers())
        registry = MetricsRegistry()
        set_global_registry(registry)
        try:
            _out, status = server.handle_request('/validate/fail',
                                                 b'broken')
            assert status == 400
            assert registry.counter_value(
                ADMISSION_REQUESTS, operation='', allowed='error') == 1
            body = json.dumps(review(pod())).encode()
            try:
                server.handle_request('/policyvalidate', body)
            except RuntimeError:
                pass
            else:
                raise AssertionError('handler exception must propagate')
            assert registry.counter_value(
                ADMISSION_REQUESTS, operation='CREATE',
                allowed='error') == 1
        finally:
            set_global_registry(None)
