"""verifyImages engine tests (reference behavior:
pkg/engine/imageVerify_test.go, pkg/utils/image/infos_test.go,
pkg/utils/api/image_test.go)."""

import json

import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.engine.api import PolicyContext, RuleStatus
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.engine.image_verify import (
    IMAGE_VERIFY_ANNOTATION, ImageVerificationMetadata,
)
from kyverno_tpu.registry import MockRegistryClient
from kyverno_tpu.utils.image import get_image_info
from kyverno_tpu.utils.image_extract import extract_images_from_resource

DIGEST = 'sha256:' + 'ab' * 32


class TestImageInfo:
    def test_simple_name(self):
        info = get_image_info('nginx')
        assert (info.registry, info.path, info.name, info.tag) == \
            ('docker.io', 'nginx', 'nginx', 'latest')
        assert str(info) == 'docker.io/nginx:latest'

    def test_registry_and_tag(self):
        info = get_image_info('ghcr.io/org/app:v1.2')
        assert (info.registry, info.path, info.tag) == \
            ('ghcr.io', 'org/app', 'v1.2')

    def test_digest(self):
        info = get_image_info(f'quay.io/app@{DIGEST}')
        assert info.digest == DIGEST
        assert str(info) == f'quay.io/app@{DIGEST}'

    def test_port_registry(self):
        info = get_image_info('localhost:5000/app:1')
        assert (info.registry, info.path, info.tag) == \
            ('localhost:5000', 'app', '1')

    def test_bad_image(self):
        with pytest.raises(ValueError):
            get_image_info('Nginx:bad tag::')

    def test_no_registry_mutation(self):
        info = get_image_info('nginx', enable_default_registry_mutation=False)
        assert info.registry == ''
        assert str(info) == 'nginx:latest'


class TestExtractors:
    def test_pod_containers(self):
        pod = {'kind': 'Pod', 'spec': {
            'containers': [{'name': 'a', 'image': 'nginx:1'}],
            'initContainers': [{'name': 'b', 'image': 'busybox:2'}]}}
        infos = extract_images_from_resource(pod)
        assert str(infos['containers']['a']) == 'docker.io/nginx:1'
        assert str(infos['initContainers']['b']) == 'docker.io/busybox:2'
        assert infos['containers']['a'].pointer == '/spec/containers/0/image'

    def test_deployment_template(self):
        dep = {'kind': 'Deployment', 'spec': {'template': {'spec': {
            'containers': [{'name': 'c', 'image': 'redis:7'}]}}}}
        infos = extract_images_from_resource(dep)
        assert infos['containers']['c'].pointer == \
            '/spec/template/spec/containers/0/image'

    def test_cronjob(self):
        cj = {'kind': 'CronJob', 'spec': {'jobTemplate': {'spec': {
            'template': {'spec': {'containers': [
                {'name': 'c', 'image': 'job:1'}]}}}}}}
        infos = extract_images_from_resource(cj)
        assert 'c' in infos['containers']

    def test_custom_extractor(self):
        res = {'kind': 'Task', 'spec': {'steps': [
            {'name': 's1', 'image': 'tool:3'}]}}
        configs = {'Task': [{'path': '/spec/steps/*', 'value': 'image',
                             'key': 'name'}]}
        infos = extract_images_from_resource(res, configs)
        assert str(infos['custom']['s1']) == 'docker.io/tool:3'


def _pod(image, annotations=None):
    meta = {'name': 'p', 'namespace': 'default'}
    if annotations:
        meta['annotations'] = annotations
    return {'apiVersion': 'v1', 'kind': 'Pod', 'metadata': meta,
            'spec': {'containers': [{'name': 'c', 'image': image}]}}


def _policy(image_verify):
    return Policy({
        'apiVersion': 'kyverno.io/v1', 'kind': 'ClusterPolicy',
        'metadata': {'name': 'verify',
                     'annotations': {
                         'pod-policies.kyverno.io/autogen-controllers':
                         'none'}},
        'spec': {'rules': [{
            'name': 'check-sig',
            'match': {'any': [{'resources': {'kinds': ['Pod']}}]},
            'verifyImages': [image_verify]}]}})


def _registry():
    r = MockRegistryClient()
    r.add_image('ghcr.io/org/app', DIGEST)
    r.sign('ghcr.io/org/app', 'key-1')
    r.attest('ghcr.io/org/app', {
        'predicateType': 'https://slsa.dev/provenance/v0.2',
        'predicate': {'builder': {'id': 'github-actions'}}})
    return r


class TestVerifyAndPatchImages:
    def test_signed_image_passes_and_gets_digest_patch(self):
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestors': [{'entries': [{'keys': {'publicKeys': 'key-1'}}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, ivm = Engine().verify_and_patch_images(pctx, _registry())
        rules = resp.policy_response.rules
        assert [r.status for r in rules] == [RuleStatus.PASS]
        assert ivm.data == {'ghcr.io/org/app:v1': True}
        patches = rules[0].patches
        assert patches and patches[0]['path'] == '/spec/containers/0/image'
        assert patches[0]['value'] == f'ghcr.io/org/app:v1@{DIGEST}'

    def test_wrong_key_fails(self):
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestors': [{'entries': [{'keys': {'publicKeys': 'other'}}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, ivm = Engine().verify_and_patch_images(pctx, _registry())
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.FAIL]
        assert ivm.data == {'ghcr.io/org/app:v1': False}

    def test_unmatched_image_skips(self):
        policy = _policy({
            'imageReferences': ['quay.io/*'],
            'attestors': [{'entries': [{'keys': {'publicKeys': 'key-1'}}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, _ = Engine().verify_and_patch_images(pctx, _registry())
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.SKIP]

    def test_attestor_count_m_of_n(self):
        registry = _registry()
        registry.sign('ghcr.io/org/app', 'key-2')
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestors': [{'count': 1, 'entries': [
                {'keys': {'publicKeys': 'nope'}},
                {'keys': {'publicKeys': 'key-2'}}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, _ = Engine().verify_and_patch_images(pctx, registry)
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.PASS]

    def test_attestation_conditions(self):
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestations': [{
                'predicateType': 'https://slsa.dev/provenance/v0.2',
                'conditions': [{'all': [{
                    'key': '{{ builder.id }}',
                    'operator': 'Equals',
                    'value': 'github-actions'}]}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, _ = Engine().verify_and_patch_images(pctx, _registry())
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.PASS], resp.policy_response.rules

    def test_attestation_condition_mismatch_fails(self):
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestations': [{
                'predicateType': 'https://slsa.dev/provenance/v0.2',
                'conditions': [{'all': [{
                    'key': '{{ builder.id }}',
                    'operator': 'Equals',
                    'value': 'jenkins'}]}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, _ = Engine().verify_and_patch_images(pctx, _registry())
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.FAIL]

    def test_missing_predicate_type_fails(self):
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestations': [{
                'predicateType': 'https://example.com/unknown',
            }]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, _ = Engine().verify_and_patch_images(pctx, _registry())
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.FAIL]

    def test_legacy_image_key_form(self):
        policy = _policy({'image': 'ghcr.io/org/*', 'key': 'key-1'})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp, _ = Engine().verify_and_patch_images(pctx, _registry())
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.PASS]

    def test_previously_verified_annotation_skips(self):
        ann = {IMAGE_VERIFY_ANNOTATION:
               json.dumps({'ghcr.io/org/app:v1': True})}
        policy = _policy({
            'imageReferences': ['ghcr.io/org/*'],
            'attestors': [{'entries': [{'keys': {'publicKeys': 'nope'}}]}]})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1', ann))
        resp, _ = Engine().verify_and_patch_images(pctx, _registry())
        # previously verified: no rule response emitted for the image
        assert resp.policy_response.rules == []


class TestValidateMode:
    def test_audit_checks_annotation(self):
        policy = _policy({'imageReferences': ['ghcr.io/org/*'],
                          'required': True, 'verifyDigest': False})
        pod = _pod(f'ghcr.io/org/app:v1')
        pctx = PolicyContext(policy=policy, new_resource=pod)
        resp = Engine().validate(pctx)
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.FAIL]

    def test_audit_passes_with_annotation(self):
        ann = {IMAGE_VERIFY_ANNOTATION:
               json.dumps({'ghcr.io/org/app:v1': True})}
        policy = _policy({'imageReferences': ['ghcr.io/org/*'],
                          'required': True, 'verifyDigest': False})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1', ann))
        resp = Engine().validate(pctx)
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.PASS]

    def test_verify_digest_fails_without_digest(self):
        policy = _policy({'imageReferences': ['ghcr.io/org/*'],
                          'required': False, 'verifyDigest': True})
        pctx = PolicyContext(policy=policy,
                             new_resource=_pod('ghcr.io/org/app:v1'))
        resp = Engine().validate(pctx)
        assert [r.status for r in resp.policy_response.rules] == \
            [RuleStatus.FAIL]
        assert 'missing digest' in resp.policy_response.rules[0].message


class TestIVM:
    def test_annotation_patches(self):
        ivm = ImageVerificationMetadata({'img:1': True})
        patches = ivm.annotation_patches({'metadata': {}})
        assert patches[0] == {'op': 'add', 'path': '/metadata/annotations',
                              'value': {}}
        assert patches[1]['path'] == \
            '/metadata/annotations/kyverno.io~1verify-images'
