"""Device-vs-host equivalence for the compiled PSS check library
(compiler/pss_compile.py vs pss/checks.py)."""

import random

import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.ir import STATUS_HOST
from kyverno_tpu.compiler.scan import BatchScanner
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine

PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: pss-baseline
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: baseline
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        podSecurity:
          level: baseline
          version: latest
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: pss-restricted
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: restricted
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        podSecurity:
          level: restricted
          version: latest
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: pss-deployments
spec:
  rules:
    - name: restricted-deploy
      match: {any: [{resources: {kinds: [Deployment]}}]}
      validate:
        podSecurity:
          level: restricted
          version: latest
"""


def load_pack():
    return [Policy(d) for d in yaml.safe_load_all(PACK)]


_CAPS = ['NET_ADMIN', 'CHOWN', 'KILL', 'ALL', 'SETUID', 'SYS_TIME',
         'NET_BIND_SERVICE']
_SECCOMP = ['RuntimeDefault', 'Localhost', 'Unconfined', None, 'Other']


def make_pod(rng):
    containers = []
    for i in range(rng.randint(1, 3)):
        c = {'name': f'c{i}', 'image': 'app:v1'}
        sc = {}
        if rng.random() < 0.3:
            sc['privileged'] = rng.choice([True, False, 'true', 1])
        if rng.random() < 0.5:
            sc['allowPrivilegeEscalation'] = rng.choice(
                [True, False, None, 'false'])
        if rng.random() < 0.5:
            caps = {}
            if rng.random() < 0.8:
                caps['add'] = rng.sample(_CAPS, rng.randint(0, 3))
            if rng.random() < 0.8:
                caps['drop'] = rng.choice(
                    [['ALL'], [], ['KILL'], ['ALL', 'KILL'], None])
            sc['capabilities'] = caps
        if rng.random() < 0.4:
            sc['runAsNonRoot'] = rng.choice([True, False, None, 'true'])
        if rng.random() < 0.3:
            sc['runAsUser'] = rng.choice([0, 1000, 0.0, False, '0'])
        if rng.random() < 0.3:
            sc['seccompProfile'] = {'type': rng.choice(_SECCOMP)}
        if rng.random() < 0.2:
            sc['seLinuxOptions'] = {
                'type': rng.choice(['container_t', 'spc_t', '', None]),
                'user': rng.choice(['', 'sys', None]),
            }
        if rng.random() < 0.15:
            sc['procMount'] = rng.choice(['Default', 'Unmasked', '', None])
        if rng.random() < 0.1:
            sc['windowsOptions'] = {'hostProcess': rng.choice(
                [True, False, 'true'])}
        if sc:
            c['securityContext'] = sc
        if rng.random() < 0.3:
            c['ports'] = [{'containerPort': 80,
                           'hostPort': rng.choice([0, 80, None])}]
        containers.append(c)
    spec = {'containers': containers}
    if rng.random() < 0.2:
        spec['initContainers'] = [dict(containers[0], name='init0')]
    if rng.random() < 0.15:
        spec['hostNetwork'] = rng.choice([True, False, 1, ''])
    if rng.random() < 0.1:
        spec['hostPID'] = True
    if rng.random() < 0.3:
        vols = []
        for v in range(rng.randint(1, 2)):
            vols.append(rng.choice([
                {'name': f'v{v}', 'emptyDir': {}},
                {'name': f'v{v}', 'hostPath': {'path': '/x'}},
                {'name': f'v{v}', 'nfs': {'server': 's', 'path': '/'}},
                {'name': f'v{v}', 'configMap': {'name': 'cm'}}]))
        spec['volumes'] = vols
    if rng.random() < 0.2:
        spec['securityContext'] = {
            'runAsNonRoot': rng.choice([True, False, None]),
            'sysctls': rng.choice([
                None, [], [{'name': 'kernel.shm_rmid_forced', 'value': '1'}],
                [{'name': 'kernel.msgmax', 'value': '1'}]]),
        }
    pod = {'apiVersion': 'v1', 'kind': 'Pod',
           'metadata': {'name': f'p{rng.randint(0, 999)}', 'namespace': 'd'},
           'spec': spec}
    if rng.random() < 0.15:
        pod['metadata']['annotations'] = {
            'container.apparmor.security.beta.kubernetes.io/c0':
                rng.choice(['runtime/default', 'localhost/x', 'unconfined',
                            '']),
            'other': 'x'}
    return pod


def make_deployment(rng):
    pod = make_pod(rng)
    return {'apiVersion': 'apps/v1', 'kind': 'Deployment',
            'metadata': {'name': 'd', 'namespace': 'd'},
            'spec': {'replicas': 1,
                     'template': {'metadata': pod['metadata'],
                                  'spec': pod['spec']}}}


class TestPSSCompile:
    def test_pack_fully_compiles(self):
        cps = compile_policies(load_pack())
        assert cps.host_rules == [], \
            [r.get('name') for _, r, _ in cps.host_rules]
        assert len(cps.programs) == 3

    def test_excludes_fall_back_to_host(self):
        policy = Policy(yaml.safe_load("""
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata: {name: x, annotations: {pod-policies.kyverno.io/autogen-controllers: none}}
spec:
  rules:
    - name: r
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        podSecurity:
          level: baseline
          exclude: [{controlName: Capabilities}]
"""))
        cps = compile_policies([policy])
        assert len(cps.host_rules) == 1


class TestPSSEquivalence:
    def test_device_vs_host_fuzz(self):
        policies = load_pack()
        engine = Engine()
        rng = random.Random(23)
        resources = [make_pod(rng) for _ in range(150)] + \
                    [make_deployment(rng) for _ in range(50)]
        scanner = BatchScanner(policies)
        scanned = scanner.scan(resources)
        for resource, responses in zip(resources, scanned):
            host = {}
            for policy in policies:
                resp = engine.apply_background_checks(
                    PolicyContext(policy, new_resource=resource))
                if resp.policy_response.rules:
                    host[policy.name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            got = {}
            for resp in responses:
                if resp.policy_response.rules:
                    got[resp.policy_response.policy_name] = {
                        r.name: (r.status, r.message)
                        for r in resp.policy_response.rules}
            assert got == host, f'divergence on {resource}'

    def test_device_decides_most(self):
        policies = load_pack()
        rng = random.Random(29)
        resources = [make_pod(rng) for _ in range(100)]
        scanner = BatchScanner(policies)
        status, detail, match = scanner.scan_statuses(resources)
        applicable = match.sum()
        host_rate = (match & (status == STATUS_HOST)).sum() / max(
            applicable, 1)
        assert host_rate < 0.05, f'device host-fallback rate {host_rate:.2f}'
