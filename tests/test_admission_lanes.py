"""Per-row admission lanes (compiler/admission.py + ops/eval.py).

Pins the heterogeneous-batching contract: for admission-dependent
rules in the lane vocabulary the jitted evaluator decides
subject/role match in-graph from per-row lanes, bit-identical to the
host matcher (the oracle, reachable via ``KTPU_ADM_LANES=0``); rows
whose admission tuples do not intern exactly fall back per-row under
the ``admission_unencodable`` taxonomy reason; and the lanes never add
an XLA input signature (executable census stays at the canonical
capacities).  CPU-only, tier-1.
"""

import os

import numpy as np
import pytest
import yaml

from kyverno_tpu.api.policy import Policy
from kyverno_tpu.compiler import admission as admlanes
from kyverno_tpu.compiler.compile import compile_policies
from kyverno_tpu.compiler.scan import BatchScanner, next_scanner_serial
from kyverno_tpu.engine.api import PolicyContext
from kyverno_tpu.engine.engine import Engine
from kyverno_tpu.observability import coverage

POLICIES = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-team
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: require-team
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "label 'team' is required"
        pattern:
          metadata: {labels: {team: "?*"}}
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: admins-only-privileged
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: admins-only
      match:
        any:
          - resources: {kinds: [Pod]}
            subjects:
              - {kind: Group, name: system:masters}
              - {kind: User, name: alice}
              - {kind: ServiceAccount, name: deployer, namespace: ci}
      validate: {message: "privileged path is admin-only", deny: {}}
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: exempt-bots
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: exempt-bots
      match: {any: [{resources: {kinds: [Pod]}, clusterRoles: [bot-role]}]}
      exclude: {any: [{subjects: [{kind: Group, name: trusted-bots}]}]}
      validate: {message: "bots must be trusted", deny: {}}
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: roles-gate
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: roles-gate
      match:
        all:
          - resources: {kinds: [Pod]}
            roles: [ns-admin]
      validate: {message: "role-gated", deny: {}}
"""

#: a userinfo rule with a label selector is OUTSIDE the lane
#: vocabulary (selector + roles) — must stay on the host matcher
INELIGIBLE = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: selector-and-roles
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  validationFailureAction: enforce
  rules:
    - name: selector-and-roles
      match:
        any:
          - resources:
              kinds: [Pod]
              selector: {matchLabels: {tier: web}}
            roles: [ops]
      validate: {message: "selector+roles", deny: {}}
"""


def pod(name, labels=None, ns='default'):
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': name, 'namespace': ns,
                         'labels': labels or {}},
            'spec': {'containers': [{'name': 'c', 'image': 'nginx'}]}}


def adm(username, groups=(), roles=(), croles=(), egr=(), op='CREATE'):
    info = {'roles': list(roles), 'clusterRoles': list(croles),
            'userInfo': {'username': username, 'groups': list(groups)}}
    return (info, list(egr), {}, op)


ADMISSIONS = [
    adm('alice'),                                       # User subject
    adm('bob', groups=['system:masters']),              # Group subject
    adm('carol', groups=['dev']),                       # no admin hit
    adm('system:serviceaccount:ci:deployer'),           # SA subject
    adm('robo', croles=['bot-role']),                   # croles, untrusted
    adm('robo2', groups=['trusted-bots'],
        croles=['bot-role']),                           # excluded by block
    adm('dana', roles=['ns-admin']),                    # roles gate
    adm('edith', groups=['dev'], croles=['bot-role'],
        egr=['dev']),                                   # excluded groups
    adm('frank', groups=['x' * 80]),                    # out-of-vocab key
]


def host_oracle(policies, doc, a):
    engine = Engine()
    out = []
    for p in policies:
        pctx = PolicyContext(p, new_resource=doc,
                             admission_info=a[0],
                             exclude_group_roles=a[1],
                             admission_operation=a[3])
        out.append(engine.validate(pctx))
    return out


def sig(resps):
    """Comparable rule signature, empty responses dropped (the scanner
    contract: policies with at least one applicable rule)."""
    return [[(rr.name, str(rr.status), rr.message)
             for rr in er.policy_response.rules] for er in resps
            if er.policy_response.rules]


@pytest.fixture(scope='module')
def policies():
    return [Policy(d) for d in yaml.safe_load_all(POLICIES) if d]


@pytest.fixture(scope='module')
def scanner(policies):
    return BatchScanner(policies)


class TestCompileAdmission:
    def test_eligible_programs_lowered(self, policies):
        cps = compile_policies(policies)
        table = admlanes.compile_admission(cps)
        assert table is not None
        names = {cps.programs[p.j].rule_name for p in table.programs}
        # require-team has no userinfo: admission-invariant, not lowered
        assert names == {'admins-only', 'exempt-bots', 'roles-gate'}
        # exact interning, no hashes
        assert set(table.vocab) == {
            'system:masters', 'alice',
            'system:serviceaccount:ci:deployer', 'bot-role',
            'trusted-bots', 'ns-admin'}

    def test_selector_plus_roles_stays_on_host(self):
        pols = [Policy(d) for d in yaml.safe_load_all(INELIGIBLE) if d]
        table = admlanes.compile_admission(compile_policies(pols))
        assert table is None

    def test_admission_invariant_set_has_no_table(self):
        pols = [Policy(d) for d in yaml.safe_load_all(POLICIES) if d][:1]
        assert admlanes.compile_admission(compile_policies(pols)) is None

    def test_knob_disables(self, policies, monkeypatch):
        monkeypatch.setenv('KTPU_ADM_LANES', '0')
        assert admlanes.compile_admission(
            compile_policies(policies)) is None


class TestRowEncoding:
    def _table(self, policies):
        return admlanes.compile_admission(compile_policies(policies))

    def test_exact_interning_and_flags(self, policies):
        table = self._table(policies)
        plan = admlanes.encode_rows(table, ADMISSIONS)
        assert plan.valid.all() and not plan.unencodable.any()
        v = table.vocab
        # alice (row 0): username interned, groups empty
        assert plan.lanes['__adm_user__'][0] == v['alice']
        # bob (row 1): system:masters group id present
        assert v['system:masters'] in set(
            plan.lanes['__adm_groups__'][1].tolist())
        # frank (row 8): out-of-vocabulary values intern to -1
        assert plan.lanes['__adm_user__'][8] == -1
        assert (plan.lanes['__adm_groups__'][8] == -1).all()
        # edith (row 7) is in her own exclude_group_roles
        assert plan.lanes['__adm_excluded__'][7] == 1
        assert plan.lanes['__adm_excluded__'][0] == 0

    def test_unencodable_rows(self, policies):
        table = self._table(policies)
        rows = [adm('u'), adm('u', groups=[1]),          # non-str group
                ('not-a-tuple',),                        # malformed
                adm('u', roles=[None])]                  # non-str role
        plan = admlanes.encode_rows(table, rows)
        assert plan.valid.tolist() == [True, False, False, False]
        assert plan.unencodable.tolist() == [False, True, True, True]

    def test_old_rows_excluded_without_taxonomy(self, policies):
        table = self._table(policies)
        plan = admlanes.encode_rows(table, [adm('a'), adm('b')],
                                    old_flags=[False, True])
        assert plan.valid.tolist() == [True, False]
        assert not plan.unencodable.any()

    def test_lane_width_overflow_is_unencodable(self, policies):
        table = self._table(policies)
        # more IN-VOCABULARY ids than the lane holds is impossible with
        # this vocab (6 entries < width); simulate via monkey vocab
        big = admlanes.AdmissionTable(
            table.programs, table.atoms,
            {f'g{i}': i for i in range(admlanes.GROUPS_W + 4)})
        row = adm('u', groups=[f'g{i}'
                               for i in range(admlanes.GROUPS_W + 1)])
        plan = admlanes.encode_rows(big, [row])
        assert plan.unencodable.tolist() == [True]


class TestBitIdentity:
    def _scan(self, scanner, policies, resources, admissions):
        pctxs = {
            id(doc): PolicyContext(policies[0], new_resource=doc,
                                   admission_info=a[0],
                                   exclude_group_roles=a[1],
                                   admission_operation=a[3])
            for doc, a in zip(resources, admissions)}
        return scanner.scan(
            resources,
            contexts=[{'request': {'object': d}} for d in resources],
            admissions=admissions,
            pctx_factory=lambda doc: pctxs[id(doc)])

    def test_mixed_rows_match_host_oracle(self, scanner, policies):
        resources = [pod(f'p{i}', {'team': 'x'} if i % 2 else {})
                     for i in range(len(ADMISSIONS))]
        rows = self._scan(scanner, policies, resources, ADMISSIONS)
        for i, (doc, a) in enumerate(zip(resources, ADMISSIONS)):
            assert sig(rows[i]) == sig(host_oracle(policies, doc, a)), i

    def test_unencodable_row_still_exact(self, scanner, policies):
        admissions = [adm('ok-user'), adm('weird', groups=[42])]
        resources = [pod('p0'), pod('p1')]
        rows = self._scan(scanner, policies, resources, admissions)
        for i, (doc, a) in enumerate(zip(resources, admissions)):
            assert sig(rows[i]) == sig(host_oracle(policies, doc, a)), i

    def test_per_row_equals_row_at_a_time(self, scanner, policies):
        resources = [pod(f'q{i}') for i in range(len(ADMISSIONS))]
        batched = self._scan(scanner, policies, resources, ADMISSIONS)
        for i, (doc, a) in enumerate(zip(resources, ADMISSIONS)):
            [single] = self._scan(scanner, policies, [doc], [a])
            assert sig(batched[i]) == sig(single), i

    def test_lanes_off_is_bit_identical(self, scanner, policies,
                                        monkeypatch):
        resources = [pod(f'r{i}', {'team': 't'})
                     for i in range(len(ADMISSIONS))]
        on = self._scan(scanner, policies, resources, ADMISSIONS)
        monkeypatch.setenv('KTPU_ADM_LANES', '0')
        off_scanner = BatchScanner(policies)
        assert off_scanner._adm is None
        off = self._scan(off_scanner, policies, resources, ADMISSIONS)
        assert [sig(a) for a in on] == [sig(b) for b in off]

    def test_background_scan_unaffected(self, scanner, policies):
        resources = [pod('bg0'), pod('bg1', {'team': 'x'})]
        rows = scanner.scan(resources)
        engine = Engine()
        for i, doc in enumerate(resources):
            want = [engine.apply_background_checks(
                PolicyContext(p, new_resource=doc)) for p in policies]
            assert sig(rows[i]) == sig(want)


class TestLedgerAndShapes:
    def test_unencodable_rows_hit_taxonomy(self, scanner, policies):
        from kyverno_tpu.observability.metrics import MetricsRegistry
        ledger = coverage.configure(MetricsRegistry())
        try:
            admissions = [adm('fine'), adm('bad', groups=[3]),
                          adm('bad2', croles=[object()])]
            resources = [pod(f'x{i}') for i in range(3)]
            pctxs = {id(d): PolicyContext(policies[0], new_resource=d)
                     for d in resources}
            scanner.scan(resources,
                         contexts=[{'request': {'object': d}}
                                   for d in resources],
                         admissions=admissions,
                         pctx_factory=lambda doc: pctxs[id(doc)])
            fallbacks = ledger.report()['fallbacks']
            assert fallbacks.get('validate', {}).get(
                coverage.REASON_ADMISSION_UNENCODABLE) == 2
        finally:
            coverage.disable()

    def test_reason_is_in_taxonomy(self):
        assert coverage.REASON_ADMISSION_UNENCODABLE in coverage.REASONS

    def test_lanes_add_no_input_signatures(self, policies):
        """Occupancies 1..N, mixed users, AND a no-admission background
        scan must reuse the canonical-capacity signatures — admission
        lanes ride every dispatch (zero-filled when absent), so the
        executable census cannot depend on traffic mix."""
        from kyverno_tpu.compiler import aot
        scanner = BatchScanner(policies)
        seen = set()
        orig = aot.executable_cache_key

        def spy(fingerprint, packed, extra=()):
            seen.add(tuple((n, str(v.dtype), tuple(v.shape))
                           for n, v in sorted(packed.items())))
            return orig(fingerprint, packed, extra)

        aot.executable_cache_key = spy
        try:
            for occ in (1, 3, 7):
                docs = [pod(f's{occ}-{i}') for i in range(occ)]
                admissions = [adm(f'user-{occ}-{i}')
                              for i in range(occ)]
                pctxs = {id(d): PolicyContext(policies[0],
                                              new_resource=d)
                         for d in docs}
                scanner.scan(docs,
                             contexts=[{'request': {'object': d}}
                                       for d in docs],
                             admissions=admissions,
                             pctx_factory=lambda doc: pctxs[id(doc)])
            scanner.scan([pod('census-bg')])
        finally:
            aot.executable_cache_key = orig
        from kyverno_tpu.compiler.shapes import canonical_caps
        assert len(seen) <= len(canonical_caps())

    def test_scanner_serials_are_monotonic(self, policies):
        a = next_scanner_serial()
        b = next_scanner_serial()
        assert b > a
        s1 = BatchScanner(policies[:1])
        s2 = BatchScanner(policies[:1])
        assert s2.serial > s1.serial
        assert s1.supports_row_admissions


class TestAdmissionKeyCanonicalization:
    def test_list_order_is_canonicalized(self):
        from kyverno_tpu.serving.batcher import admission_key
        a = adm('u', groups=['b', 'a'], roles=['r2', 'r1'])
        b = adm('u', groups=['a', 'b'], roles=['r1', 'r2'])
        assert admission_key(a) == admission_key(b)

    def test_top_level_positions_are_preserved(self):
        from kyverno_tpu.serving.batcher import admission_key
        create = adm('u', op='CREATE')
        update = adm('u', op='UPDATE')
        assert admission_key(create) != admission_key(update)
        other_user = adm('v')
        assert admission_key(adm('u')) != admission_key(other_user)

    def test_deterministic_json(self):
        from kyverno_tpu.serving.batcher import admission_key
        key = admission_key(adm('u', groups=['g']))
        import json
        assert json.loads(key)  # stable, parseable JSON
        assert admission_key(adm('u', groups=['g'])) == key
