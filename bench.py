#!/usr/bin/env python
"""Background-scan throughput benchmark on the reference policy packs.

Measures the north-star workload (BASELINE.md): background-scan of
synthetic Pods against the reference's real policy packs —
``test/best_practices`` plus the rendered ``charts/kyverno-policies``
baseline+restricted profiles — reporting absolute decisions/sec on the
available accelerator and the ratio vs the pure-host Python engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "decisions/s", "vs_baseline": N}
vs_baseline is measured against the BASELINE.json north star of 50k
decisions/s on a v5e-4 slice -> 12.5k/s per chip.

The TPU backend is probed in a subprocess first (backend init failures
are sticky in-process); on failure the bench still runs on CPU and the
JSON line records the platform, so a number always exists.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

PER_CHIP_TARGET = 50_000 / 4  # north star: 50k/s on v5e-4

# kept for __graft_entry__: a small self-contained pack + pod generator
PACK = """
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: disallow-latest-tag
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: require-image-tag
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "An image tag is required."
        pattern:
          spec:
            containers:
              - image: "!*:latest"
---
apiVersion: kyverno.io/v1
kind: ClusterPolicy
metadata:
  name: require-resources
  annotations: {pod-policies.kyverno.io/autogen-controllers: none}
spec:
  rules:
    - name: validate-resources
      match: {any: [{resources: {kinds: [Pod]}}]}
      validate:
        message: "resource requests and limits required"
        pattern:
          spec:
            containers:
              - resources:
                  requests:
                    memory: "?*"
                    cpu: "?*"
"""

_IMAGES = ['nginx:1.25.3', 'nginx:latest', 'ghcr.io/org/app:v2.1',
           'redis:7', 'docker.io/library/busybox', 'gcr.io/proj/svc:prod',
           'app', 'registry.internal:5000/team/api:canary']
_CAPS = ['NET_ADMIN', 'SYS_TIME', 'CHOWN', 'KILL', 'AUDIT_WRITE', 'ALL']


def make_pod(rng, i: int) -> dict:
    """Synthetic Pod with a realistic violation mix."""
    n_containers = 1 + (i % 3)
    containers = []
    for c in range(n_containers):
        cont = {'name': f'c{c}', 'image': _IMAGES[(i + c) % len(_IMAGES)]}
        if rng.random() < 0.8:
            cont['resources'] = {
                'requests': {'memory': '64Mi', 'cpu': '100m'},
                'limits': {'memory': rng.choice(['128Mi', '2Gi', '8Gi'])},
            }
        if rng.random() < 0.5:
            sc = {}
            if rng.random() < 0.5:
                sc['allowPrivilegeEscalation'] = rng.random() < 0.3
            if rng.random() < 0.3:
                sc['privileged'] = rng.random() < 0.3
            if rng.random() < 0.4:
                sc['capabilities'] = {
                    'add': rng.sample(_CAPS, rng.randint(1, 2)),
                    'drop': rng.choice([['ALL'], [], ['KILL']]),
                }
            if rng.random() < 0.4:
                sc['runAsNonRoot'] = rng.random() < 0.7
            cont['securityContext'] = sc
        if rng.random() < 0.3:
            cont['ports'] = [{'containerPort': rng.choice([80, 8080, 443]),
                              'hostPort': rng.choice([0, 80, 9000])}]
        containers.append(cont)
    spec = {'containers': containers}
    if rng.random() < 0.1:
        spec['hostNetwork'] = True
    if rng.random() < 0.08:
        spec['hostPID'] = True
    if rng.random() < 0.15:
        spec['volumes'] = [{'name': 'v0', 'hostPath': {'path': '/var/run'}}
                           if rng.random() < 0.5 else
                           {'name': 'v0', 'emptyDir': {}}]
    if rng.random() < 0.2:
        spec['securityContext'] = {'sysctls': [
            {'name': rng.choice(['kernel.shm_rmid_forced',
                                 'net.core.rmem_max']),
             'value': '1'}]}
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': f'pod-{i}', 'namespace': f'ns-{i % 7}',
                         'labels': {'app': f'app-{i % 11}'}},
            'spec': spec}


def probe_platform() -> str:
    """Probe the default JAX backend in a subprocess (init failures are
    sticky in-process); returns the platform to use."""
    env = dict(os.environ)
    code = 'import jax; print(jax.default_backend())'
    for attempt in range(2):
        try:
            out = subprocess.run([sys.executable, '-c', code], env=env,
                                 capture_output=True, text=True, timeout=180)
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            pass
        time.sleep(3)
    return 'cpu'


def load_policy_pack():
    import glob
    import yaml
    from kyverno_tpu.api.policy import Policy
    docs = []
    for f in sorted(glob.glob('/root/reference/test/best_practices/*.yaml')):
        for d in yaml.safe_load_all(open(f)):
            if d and d.get('kind') in ('ClusterPolicy', 'Policy'):
                docs.append(d)
    try:
        from kyverno_tpu.utils.helmlite import load_chart_policies
        docs += load_chart_policies(
            '/root/reference/charts/kyverno-policies',
            profiles=('baseline', 'restricted'))
    except Exception as e:  # noqa: BLE001 - charts are additive
        print(f'chart load failed: {e}', file=sys.stderr)
    return [Policy(d) for d in docs]


def cache_probe(platform: str) -> float:
    """Second-process warm-up with the persistent XLA compilation cache
    populated: build the full-pack scanner and run one chunk-shaped scan.
    Returns the compile+warm seconds the fresh process paid."""
    code = (
        'import sys, time, random; sys.path.insert(0, %r)\n'
        'import bench\n'
        'from kyverno_tpu.compiler.scan import BatchScanner\n'
        't0 = time.time()\n'
        'scanner = BatchScanner(bench.load_policy_pack())\n'
        'rng = random.Random(0)\n'
        'pods = [bench.make_pod(rng, i) for i in range(scanner.CHUNK)]\n'
        'scanner.scan_statuses(pods)\n'
        'print(f"CACHEPROBE {time.time() - t0:.2f}")\n'
    ) % os.path.dirname(os.path.abspath(__file__))
    try:
        out = subprocess.run([sys.executable, '-c', code],
                             capture_output=True, text=True, timeout=600)
        for line in out.stdout.splitlines():
            if line.startswith('CACHEPROBE'):
                return float(line.split()[1])
    except Exception:  # noqa: BLE001 - probe is informational
        pass
    return -1.0


def run_bench(n: int, platform: str) -> dict:
    import random
    from kyverno_tpu.compiler.scan import BatchScanner
    from kyverno_tpu.compiler.ir import (STATUS_HOST, STATUS_PASS,
                                         STATUS_SKIP_PRECOND, STATUS_VAR_ERR)
    from kyverno_tpu.reports.types import new_background_scan_report
    from kyverno_tpu.reports.results import set_responses

    policies = load_policy_pack()
    rng = random.Random(42)
    resources = [make_pod(rng, i) for i in range(n)]

    t0 = time.time()
    scanner = BatchScanner(policies)
    compile_s = time.time() - t0
    n_rules = len(scanner.cps.programs) + len(scanner.cps.host_rules)

    # warm the jit cache at the real chunk shape (and the small-bucket
    # shape) so the one-time XLA compile is excluded from steady state;
    # reported separately — a policy-set change pays this again unless
    # the persistent compilation cache hits
    warm_n = min(n, scanner.CHUNK + 1)
    t_warm = time.time()
    scanner.scan(resources[:warm_n])
    warm_s = time.time() - t_warm

    # count host materializations to keep the device-decided fraction
    # honest: every cell NOT synthesized from device outputs re-runs the
    # host engine and caps throughput
    materialized = [0]
    inner_materialize = scanner._materialize

    def counting_materialize(prog, doc):
        materialized[0] += 1
        return inner_materialize(prog, doc)
    scanner._materialize = counting_materialize

    # HEADLINE: the report-producing path — full EngineResponses with
    # host-identical messages, then BackgroundScanReport construction
    # (what reports/controllers.py BackgroundScanController.reconcile runs)
    t1 = time.time()
    out = scanner.scan(resources)
    scan_s = time.time() - t1
    decisions = sum(len(r.policy_response.rules)
                    for responses in out for r in responses)
    # rule responses produced by compiled programs (host-policy rules run
    # the host engine by design and must not dilute device_decided_frac)
    host_policy_names = {scanner.policies[i].name
                         for i in scanner._host_policy_idx}
    compiled_decisions = sum(
        len(r.policy_response.rules) for responses in out
        for r in responses
        if r.policy_response.policy_name not in host_policy_names)

    t2 = time.time()
    reports = []
    for resource, responses in zip(resources, out):
        report = new_background_scan_report(resource)
        relevant = [r for r in responses if r.policy_response.rules]
        set_responses(report, *relevant)
        reports.append(report)
    report_s = time.time() - t2
    e2e_s = scan_s + report_s
    rate = decisions / e2e_s if e2e_s > 0 else 0.0

    # the raw status sieve (no response objects), reported separately
    t3 = time.time()
    status, detail, match = scanner.scan_statuses(resources)
    sieve_s = time.time() - t3
    sieve_rate = int(match.sum()) / sieve_s if sieve_s > 0 else 0.0
    synth = (status == STATUS_PASS) | (status == STATUS_SKIP_PRECOND) | \
        (status == STATUS_VAR_ERR)
    host_status_frac = int((match & (status == STATUS_HOST)).sum()) / \
        max(int(match.sum()), 1)
    nonpass = int(match.sum()) - int((match & (status == STATUS_PASS)).sum())

    device_decided_frac = 1.0 - materialized[0] / max(compiled_decisions, 1)
    warning = None
    if device_decided_frac < 0.95:
        warning = (f'device_decided_frac dropped to '
                   f'{device_decided_frac:.3f} — host materialization is '
                   f'capping throughput')
        print(f'WARNING: {warning}', file=sys.stderr)

    # host-engine baseline on a sample (the pure-Python interpreter this
    # repo would use without the device path; the reference Go engine is
    # not runnable here -- no Go toolchain)
    sample = min(200, n)
    from kyverno_tpu.engine.engine import Engine
    from kyverno_tpu.engine.api import PolicyContext
    engine = Engine()
    t4 = time.time()
    host_dec = 0
    for doc in resources[:sample]:
        for policy in policies:
            resp = engine.apply_background_checks(
                PolicyContext(policy, new_resource=doc))
            host_dec += len(resp.policy_response.rules)
    host_s = time.time() - t4
    host_rate = host_dec / host_s if host_s > 0 else 0.0

    # admission latency through the full serving chain at ~1k policies
    # (BASELINE metric: 'p50 webhook latency @1k policies')
    lat_p50_ms, lat_p99_ms, lat_n_policies = admission_latency(
        policies, resources)

    # fresh-process warm time with the persistent compilation cache
    cache_warm_s = cache_probe(platform) \
        if os.environ.get('BENCH_CACHE_PROBE', '1') == '1' else -1.0

    result = {
        'metric': 'bg_scan_e2e_decisions_per_sec_per_chip',
        'value': round(rate, 1),
        'unit': 'decisions/s',
        'vs_baseline': round(rate / PER_CHIP_TARGET, 3),
        'platform': platform,
        'n_resources': n,
        'n_policies': len(policies),
        'n_rules': n_rules,
        'n_compiled_rules': len(scanner.cps.programs),
        'decisions': decisions,
        'n_reports': len(reports),
        'device_decided_frac': round(device_decided_frac, 4),
        'materialized': materialized[0],
        'host_status_frac': round(host_status_frac, 4),
        'nonpass_frac': round(nonpass / max(int(match.sum()), 1), 4),
        'compile_s': round(compile_s, 2),
        'warm_s': round(warm_s, 2),
        'scan_s': round(scan_s, 2),
        'report_s': round(report_s, 2),
        'cache_warm_s': round(cache_warm_s, 2),
        'sieve_decisions_per_sec': round(sieve_rate, 1),
        'host_engine_decisions_per_sec': round(host_rate, 1),
        'speedup_vs_host_engine': round(rate / host_rate, 2)
        if host_rate else None,
        'admission_p50_ms': lat_p50_ms,
        'admission_p99_ms': lat_p99_ms,
        'admission_n_policies': lat_n_policies,
    }
    if warning:
        result['warning'] = warning
    return result


def admission_latency(policies, resources, target_policies=1000,
                      samples=120):
    """p50/p99 latency of /validate through the full handler chain with
    the pack replicated to ~1k policies (enforce mode)."""
    import copy
    import json as _json
    import statistics
    from kyverno_tpu.policycache.cache import Cache
    from kyverno_tpu.api.policy import Policy
    from kyverno_tpu.webhooks.handlers import ResourceHandlers
    from kyverno_tpu.webhooks.server import WebhookServer

    replicated = []
    i = 0
    while len(replicated) < target_policies:
        for p in policies:
            doc = copy.deepcopy(p.raw)
            doc['metadata']['name'] = f"{doc['metadata']['name']}-r{i}"
            doc.setdefault('spec', {})['validationFailureAction'] = 'Enforce'
            replicated.append(Policy(doc))
            if len(replicated) >= target_policies:
                break
        i += 1
    cache = Cache()
    cache.warm_up(replicated)
    server = WebhookServer(ResourceHandlers(cache))
    lat = []
    for k in range(samples):
        doc = resources[k % len(resources)]
        review = _json.dumps({
            'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
            'request': {
                'uid': f'u{k}', 'operation': 'CREATE',
                'kind': {'group': '', 'version': 'v1',
                         'kind': doc.get('kind', '')},
                'namespace': doc['metadata'].get('namespace', ''),
                'name': doc['metadata'].get('name', ''),
                'object': doc, 'userInfo': {'username': 'bench'},
            }}).encode()
        t0 = time.time()
        server.handle('/validate/fail', review)
        lat.append((time.time() - t0) * 1000)
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    return (round(statistics.median(lat), 2), round(p99, 2),
            len(replicated))


def main() -> int:
    n = int(os.environ.get('BENCH_N', '50000'))
    platform = os.environ.get('BENCH_PLATFORM') or probe_platform()
    if platform == 'cpu':
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        import jax
        jax.config.update('jax_platforms', 'cpu')
    try:
        result = run_bench(n, platform)
    except Exception as e:  # noqa: BLE001 - always emit a JSON line
        import traceback
        traceback.print_exc()
        print(json.dumps({
            'metric': 'bg_scan_decisions_per_sec_per_chip', 'value': 0,
            'unit': 'decisions/s', 'vs_baseline': 0.0,
            'platform': platform, 'error': f'{type(e).__name__}: {e}'}))
        return 1
    print(json.dumps(result))
    return 0


if __name__ == '__main__':
    sys.exit(main())
